"""Sharded training step: the multi-chip path the driver dry-runs.

``make_train_step(config, plan)`` returns a jitted function whose inputs
and outputs are pinned to the mesh: parameters in the TP+fsdp layout from
``llama.partition_specs``, optimizer state following parameters, batch
split over dp, loss replicated.  XLA inserts the collectives (psum of
gradients over dp/fsdp, all-gathers for tp matmuls) from these shardings
-- no hand-written communication.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax

from . import llama
from ..parallel.mesh import MeshPlan, P

__all__ = ["make_train_step", "init_train_state", "language_model_loss"]


def language_model_loss(params, config, tokens,
                        moe_aux_weight: float = 0.01):
    """Next-token cross-entropy over [B, S] token batches
    (shift-by-one).  MoE configs add the GShard load-balance aux loss
    so the router learns to spread tokens across the ep-sharded
    experts."""
    cache = llama.init_cache(config, tokens.shape[0], tokens.shape[1])
    logits, _, aux = llama.prefill_with_aux.__wrapped__(
        params, config, tokens, cache,
        jnp.zeros(tokens.shape[0], dtype=jnp.int32))
    targets = tokens[:, 1:]
    logits = logits[:, :-1, :].astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(log_probs, targets[..., None],
                                 axis=-1)[..., 0]
    loss = -picked.mean()
    if config.n_experts:
        loss = loss + moe_aux_weight * aux
    return loss


def init_train_state(key, config: llama.LlamaConfig, plan: MeshPlan,
                     learning_rate: float = 3e-4):
    """Params + optimizer state, placed on the mesh."""
    optimizer = optax.adamw(learning_rate)
    param_specs = llama.partition_specs(config)
    params = jax.jit(
        lambda k: llama.init_params(k, config),
        out_shardings=jax.tree_util.tree_map(plan.shard, param_specs),
    )(key)
    opt_state = jax.jit(
        optimizer.init,
        # optimizer moments mirror parameter sharding via propagation
    )(params)
    return params, opt_state, optimizer


def make_train_step(config: llama.LlamaConfig, plan: MeshPlan,
                    optimizer=None, learning_rate: float = 3e-4):
    optimizer = optimizer or optax.adamw(learning_rate)
    param_shardings = jax.tree_util.tree_map(
        plan.shard, llama.partition_specs(config))
    batch_sharding = plan.shard(P(("dp", "fsdp"), None))

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(language_model_loss)(
            params, config, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_shardings, None, batch_sharding),
        out_shardings=(param_shardings, None, None),
        donate_argnums=(0, 1))
