"""Speech recognition, TPU-first (BASELINE config 5; reference
equivalent: examples/speech/speech_elements.py:203-239 PE_WhisperX, which
wraps the external whisperx/CUDA model -- here the ASR model is the
framework's own, functional JAX with weights resident in HBM).

Whisper-class shape, house architecture (shared with models/llama.py):

- **log-mel frontend** in pure jnp: frame -> Hann window -> rfft ->
  mel filterbank -> log, all static shapes, jittable on device;
- **encoder**: two strided 1-D convs (4x subsampling) + sinusoidal
  positions + a ``lax.scan`` over pre-norm transformer layers
  (bidirectional attention, RMSNorm + SwiGLU -- the same blocks the
  rest of the framework uses, ops/layers.py);
- **decoder**: byte-level tokens, causal self-attention plus
  cross-attention to the encoder output, scanned layers;
- **greedy transcribe** runs the whole decode as one ``lax.scan`` with
  a static token budget (no data-dependent Python control flow; EOS
  handled by masking) -- one trace, one compile per audio bucket.
  The decode is KV-CACHED: cross-attention K/V are projected once per
  utterance, self-attention K/V append to a cache (the same split-
  softmax read-only-cache pattern as models/llama.py decode), so a
  transcription costs O(S) decoder work, not the O(S^2) of re-running
  the teacher-forced decoder per emitted token;
- **StreamingAsr** transcribes live audio incrementally: push samples,
  full chunks each cost exactly one compiled dispatch (bounded
  per-chunk latency for the mic -> text path).

Audio is right-padded to a fixed chunk (``chunk_seconds``) so every
utterance compiles to the same shapes (the ShapeBucketer idea applied
to sound).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.layers import attention_decode_append, rms_norm, swiglu

__all__ = ["AsrConfig", "init_params", "log_mel", "encode",
           "transcribe", "asr_loss", "partition_specs",
           "StreamingAsr"]


@dataclasses.dataclass(frozen=True)
class AsrConfig:
    # audio frontend
    sample_rate: int = 16_000
    chunk_seconds: float = 10.0
    n_fft: int = 400              # 25 ms window
    hop: int = 160                # 10 ms hop
    n_mels: int = 80
    # model
    vocab_size: int = 260         # bytes + BOS/EOS/PAD specials
    dim: int = 384
    n_heads: int = 6
    n_encoder_layers: int = 4
    n_decoder_layers: int = 4
    hidden_dim: int = 1536
    max_text: int = 128           # static decode budget (tokens)
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    bos_token: int = 257
    eos_token: int = 258

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def n_frames(self) -> int:
        """Mel frames per chunk (before conv subsampling)."""
        return int(self.sample_rate * self.chunk_seconds) // self.hop

    @property
    def n_audio_positions(self) -> int:
        return self.n_frames // 4    # two stride-2 convs

    @classmethod
    def base(cls) -> "AsrConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "AsrConfig":
        """Test-size: milliseconds on a CPU mesh."""
        return cls(chunk_seconds=1.0, n_mels=16, dim=32, n_heads=2,
                   n_encoder_layers=2, n_decoder_layers=2, hidden_dim=64,
                   max_text=16)


def _dtype(config):
    return jnp.dtype(config.dtype)


# ---------------------------------------------------------------------------
# Log-mel frontend (static shapes, on-device).

def _mel_filterbank(config: AsrConfig) -> np.ndarray:
    """[n_fft//2+1, n_mels] triangular filters (host-side constant)."""
    n_bins = config.n_fft // 2 + 1
    f_max = config.sample_rate / 2

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mel_points = np.linspace(0.0, hz_to_mel(f_max), config.n_mels + 2)
    hz_points = mel_to_hz(mel_points)
    bins = np.floor((config.n_fft + 1) * hz_points
                    / config.sample_rate).astype(int)
    bank = np.zeros((n_bins, config.n_mels), dtype=np.float32)
    for m in range(1, config.n_mels + 1):
        left, centre, right = bins[m - 1], bins[m], bins[m + 1]
        for k in range(left, centre):
            if centre > left:
                bank[k, m - 1] = (k - left) / (centre - left)
        for k in range(centre, right):
            if right > centre:
                bank[k, m - 1] = (right - k) / (right - centre)
    return bank


def log_mel(config: AsrConfig, samples: jax.Array) -> jax.Array:
    """waveform [B, T] float32 (T = chunk worth of samples, pre-padded)
    -> log-mel [B, n_frames, n_mels]."""
    frames = config.n_frames
    window = jnp.asarray(np.hanning(config.n_fft).astype(np.float32))
    bank = jnp.asarray(_mel_filterbank(config))
    pad = config.n_fft // 2
    padded = jnp.pad(samples, ((0, 0), (pad, pad)), mode="reflect")
    # Gather strided frames: [B, n_frames, n_fft].
    starts = jnp.arange(frames) * config.hop
    index = starts[:, None] + jnp.arange(config.n_fft)[None, :]
    stacked = padded[:, index]                      # [B, F, n_fft]
    spectrum = jnp.fft.rfft(stacked * window, axis=-1)
    power = jnp.abs(spectrum) ** 2                  # [B, F, bins]
    mel = power @ bank                              # [B, F, n_mels]
    log_spec = jnp.log10(jnp.maximum(mel, 1e-10))
    log_spec = jnp.maximum(log_spec, log_spec.max() - 8.0)
    return (log_spec + 4.0) / 4.0


def pad_audio(config: AsrConfig, samples: np.ndarray) -> np.ndarray:
    """Right-pad/trim a mono waveform to exactly one chunk."""
    want = int(config.sample_rate * config.chunk_seconds)
    samples = np.asarray(samples, dtype=np.float32).reshape(-1)[:want]
    if len(samples) < want:
        samples = np.pad(samples, (0, want - len(samples)))
    return samples


# ---------------------------------------------------------------------------
# Parameters.

def init_params(key: jax.Array, config: AsrConfig) -> dict:
    c = config
    dtype = _dtype(c)
    keys = iter(jax.random.split(key, 24))

    def dense(shape, fan_in):
        return (jax.random.normal(next(keys), shape, dtype=jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    def layer_stack(n, with_cross: bool):
        hd = c.head_dim
        stack = {
            "wq": dense((n, c.dim, c.n_heads * hd), c.dim),
            "wk": dense((n, c.dim, c.n_heads * hd), c.dim),
            "wv": dense((n, c.dim, c.n_heads * hd), c.dim),
            "wo": dense((n, c.n_heads * hd, c.dim), c.n_heads * hd),
            "w_gate": dense((n, c.dim, c.hidden_dim), c.dim),
            "w_up": dense((n, c.dim, c.hidden_dim), c.dim),
            "w_down": dense((n, c.hidden_dim, c.dim), c.hidden_dim),
            "attn_norm": jnp.ones((n, c.dim), dtype=dtype),
            "mlp_norm": jnp.ones((n, c.dim), dtype=dtype),
        }
        if with_cross:
            stack.update({
                "xq": dense((n, c.dim, c.n_heads * hd), c.dim),
                "xk": dense((n, c.dim, c.n_heads * hd), c.dim),
                "xv": dense((n, c.dim, c.n_heads * hd), c.dim),
                "xo": dense((n, c.n_heads * hd, c.dim), c.n_heads * hd),
                "cross_norm": jnp.ones((n, c.dim), dtype=dtype),
            })
        return stack

    return {
        "conv1": {"w": dense((3, c.n_mels, c.dim), 3 * c.n_mels),
                  "b": jnp.zeros((c.dim,), dtype=dtype)},
        "conv2": {"w": dense((3, c.dim, c.dim), 3 * c.dim),
                  "b": jnp.zeros((c.dim,), dtype=dtype)},
        "encoder": layer_stack(c.n_encoder_layers, with_cross=False),
        "encoder_norm": jnp.ones((c.dim,), dtype=dtype),
        "embed": dense((c.vocab_size, c.dim), c.dim),
        "decoder": layer_stack(c.n_decoder_layers, with_cross=True),
        "decoder_norm": jnp.ones((c.dim,), dtype=dtype),
    }


def partition_specs(config: AsrConfig) -> dict:
    """TP layout mirroring models/llama.py: heads/hidden over tp."""
    from ..parallel.mesh import P

    def layer_specs(with_cross: bool):
        spec = {
            "wq": P(None, None, "tp"), "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"), "wo": P(None, "tp", None),
            "w_gate": P(None, None, "tp"), "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
            "attn_norm": P(None, None), "mlp_norm": P(None, None),
        }
        if with_cross:
            spec.update({"xq": P(None, None, "tp"),
                         "xk": P(None, None, "tp"),
                         "xv": P(None, None, "tp"),
                         "xo": P(None, "tp", None),
                         "cross_norm": P(None, None)})
        return spec

    return {
        "conv1": {"w": P(None, None, "tp"), "b": P("tp")},
        "conv2": {"w": P(None, None, "tp"), "b": P("tp")},
        "encoder": layer_specs(False),
        "encoder_norm": P(None),
        "embed": P(None, None),
        "decoder": layer_specs(True),
        "decoder_norm": P(None),
    }


# ---------------------------------------------------------------------------
# Model body.

def _attention(q, k, v, n_heads: int, causal: bool):
    """q [B,S,D'], k/v [B,T,D'] already projected; multi-head dense
    attention with optional causal mask; float32 softmax."""
    b, s, _ = q.shape
    t = k.shape[1]
    hd = q.shape[-1] // n_heads
    q = q.reshape(b, s, n_heads, hd)
    k = k.reshape(b, t, n_heads, hd)
    v = v.reshape(b, t, n_heads, hd)
    logits = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32) * hd ** -0.5
    if causal:
        mask = jnp.tril(jnp.ones((s, t), dtype=bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", weights.astype(v.dtype), v)
    return out.reshape(b, s, n_heads * hd)


def _sinusoid(positions: int, dim: int) -> np.ndarray:
    pos = np.arange(positions)[:, None]
    idx = np.arange(dim // 2)[None, :]
    angle = pos / (10_000 ** (2 * idx / dim))
    return np.concatenate([np.sin(angle), np.cos(angle)],
                          axis=-1).astype(np.float32)


def _conv1d(params, x, stride: int):
    """x [B, T, C] -> [B, T/stride, C'] with 'SAME' padding + GELU."""
    out = jax.lax.conv_general_dilated(
        x, params["w"].astype(x.dtype), window_strides=(stride,),
        padding="SAME", dimension_numbers=("NWC", "WIO", "NWC"))
    return jax.nn.gelu(out + params["b"].astype(x.dtype))


def encode(params: dict, config: AsrConfig, mel: jax.Array) -> jax.Array:
    """log-mel [B, F, n_mels] -> encoder states [B, F/4, D]."""
    c = config
    x = mel.astype(_dtype(c))
    x = _conv1d(params["conv1"], x, stride=2)
    x = _conv1d(params["conv2"], x, stride=2)
    positions = jnp.asarray(_sinusoid(x.shape[1], c.dim))
    x = x + positions[None].astype(x.dtype)

    def layer_step(hidden, layer):
        h = rms_norm(hidden, layer["attn_norm"], c.norm_eps)
        attn = _attention(h @ layer["wq"], h @ layer["wk"],
                          h @ layer["wv"], c.n_heads, causal=False)
        hidden = hidden + attn @ layer["wo"]
        h = rms_norm(hidden, layer["mlp_norm"], c.norm_eps)
        hidden = hidden + swiglu(h, layer["w_gate"], layer["w_up"],
                                 layer["w_down"])
        return hidden, None

    x, _ = jax.lax.scan(layer_step, x, params["encoder"])
    return rms_norm(x, params["encoder_norm"], c.norm_eps)


def _decode_states(params: dict, config: AsrConfig, tokens: jax.Array,
                   encoded: jax.Array) -> jax.Array:
    """Teacher-forced decoder pass: tokens [B, S] -> logits [B, S, V]."""
    c = config
    hidden = params["embed"][tokens]
    positions = jnp.asarray(_sinusoid(tokens.shape[1], c.dim))
    hidden = hidden + positions[None].astype(hidden.dtype)

    def layer_step(hidden, layer):
        h = rms_norm(hidden, layer["attn_norm"], c.norm_eps)
        attn = _attention(h @ layer["wq"], h @ layer["wk"],
                          h @ layer["wv"], c.n_heads, causal=True)
        hidden = hidden + attn @ layer["wo"]
        h = rms_norm(hidden, layer["cross_norm"], c.norm_eps)
        cross = _attention(h @ layer["xq"], encoded @ layer["xk"],
                           encoded @ layer["xv"], c.n_heads, causal=False)
        hidden = hidden + cross @ layer["xo"]
        h = rms_norm(hidden, layer["mlp_norm"], c.norm_eps)
        hidden = hidden + swiglu(h, layer["w_gate"], layer["w_up"],
                                 layer["w_down"])
        return hidden, None

    hidden, _ = jax.lax.scan(layer_step, hidden, params["decoder"])
    hidden = rms_norm(hidden, params["decoder_norm"], c.norm_eps)
    return hidden @ params["embed"].T


@partial(jax.jit, static_argnames=("config",))
def transcribe(params: dict, config: AsrConfig,
               samples: jax.Array) -> jax.Array:
    """Greedy decode: waveform [B, T_chunk] -> token ids [B, max_text].

    KV-cached O(S) decode (the models/llama.py pattern applied to the
    encoder-decoder): cross-attention keys/values are projected ONCE
    per utterance, each step's self-attention reads the read-only cache
    via the split-softmax append (ops/layers.py
    attention_decode_append, with K = H: plain multi-head), and the
    step's k/v pair is written back with one dynamic_update_slice.  The
    loop is a single ``lax.scan`` with a static budget; after EOS a row
    keeps emitting EOS (masked), so shapes stay static and the whole
    transcription compiles once per audio bucket.
    """
    c = config
    dtype = _dtype(c)
    encoded = encode(params, c, log_mel(c, samples))
    batch = samples.shape[0]
    hd = c.head_dim

    # Cross-attention K/V once per utterance: [L, B, T_enc, D'].
    def cross_step(_, layer):
        return None, (encoded @ layer["xk"], encoded @ layer["xv"])
    _, (xk_all, xv_all) = jax.lax.scan(cross_step, None,
                                       params["decoder"])

    cache_shape = (c.n_decoder_layers, batch, c.max_text, c.n_heads, hd)
    cache_k = jnp.zeros(cache_shape, dtype=dtype)
    cache_v = jnp.zeros(cache_shape, dtype=dtype)
    pos_table = jnp.asarray(_sinusoid(c.max_text, c.dim))
    current = jnp.full((batch,), c.bos_token, dtype=jnp.int32)
    finished = jnp.zeros((batch,), dtype=bool)

    def step(carry, i):
        current, finished, cache_k, cache_v = carry
        hidden = params["embed"][current][:, None, :] \
            + pos_table[i][None, None, :].astype(dtype)
        lengths = jnp.full((batch,), i, dtype=jnp.int32)

        def layer_step(hidden, xs):
            layer, k_cache, v_cache, xk, xv = xs
            h = rms_norm(hidden, layer["attn_norm"], c.norm_eps)
            q = (h @ layer["wq"]).reshape(batch, 1, c.n_heads, hd)
            k = (h @ layer["wk"]).reshape(batch, 1, c.n_heads, hd)
            v = (h @ layer["wv"]).reshape(batch, 1, c.n_heads, hd)
            attn = attention_decode_append(q, k_cache, v_cache, k, v,
                                           lengths)
            hidden = hidden + attn.reshape(batch, 1, -1) @ layer["wo"]
            h = rms_norm(hidden, layer["cross_norm"], c.norm_eps)
            cross = _attention(h @ layer["xq"], xk, xv, c.n_heads,
                               causal=False)
            hidden = hidden + cross @ layer["xo"]
            h = rms_norm(hidden, layer["mlp_norm"], c.norm_eps)
            hidden = hidden + swiglu(h, layer["w_gate"], layer["w_up"],
                                     layer["w_down"])
            return hidden, (k, v)

        hidden, (k_new, v_new) = jax.lax.scan(
            layer_step, hidden,
            (params["decoder"], cache_k, cache_v, xk_all, xv_all))
        hidden = rms_norm(hidden, params["decoder_norm"], c.norm_eps)
        logits = (hidden @ params["embed"].T)[:, 0]
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        next_token = jnp.where(finished, c.eos_token, next_token)
        finished = finished | (next_token == c.eos_token)
        # k_new/v_new: [L, B, 1, H, hd] -- one DUS writes every layer's
        # token at position i (read-only inside the layer scan, exactly
        # the llama decode cache discipline).
        cache_k = jax.lax.dynamic_update_slice(cache_k, k_new,
                                               (0, 0, i, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v_new,
                                               (0, 0, i, 0, 0))
        return (next_token, finished, cache_k, cache_v), next_token

    (_, _, _, _), emitted = jax.lax.scan(
        step, (current, finished, cache_k, cache_v),
        jnp.arange(c.max_text))
    return emitted.T                                # [B, max_text]


def decode_text(config: AsrConfig, token_row) -> str:
    """Token ids -> text (byte-level; specials stripped)."""
    data = bytearray()
    for token in np.asarray(token_row).tolist():
        if token == config.eos_token:
            break
        if 0 <= token < 256:
            data.append(token)
    return data.decode("utf-8", errors="replace")


def encode_text(config: AsrConfig, text: str) -> list[int]:
    return list(text.encode("utf-8"))[:config.max_text - 1]


class StreamingAsr:
    """Incremental transcription for live audio (the ``mic://`` -> text
    path; reference equivalent: examples/speech/speech_elements.py
    PE_WhisperX's LRU sliding window at :53-84, which batch-reprocesses
    the window -- here each decode costs exactly ONE compiled dispatch).

    Usage::

        streamer = StreamingAsr(params, config, hop_seconds=1.0,
                                endpoint_silence=0.5)
        final = streamer.push(mic_samples)   # FINALIZED text (see below)
        live = streamer.partial_text         # revisable hypothesis
        final += streamer.flush()            # finalize the tail

    Three latency mechanisms (VERDICT r3 item 6):

    - **sub-chunk partial decode**: with ``hop_seconds`` set, every
      hop's worth of new audio re-decodes the buffered (zero-padded)
      window -- the rolling re-encode strategy, one compiled shape --
      updating ``partial_text`` (the current revisable hypothesis) and
      ``stable_text`` (the prefix two consecutive hypotheses agree on).
      First-word latency is bounded by the hop, not ``chunk_seconds``
      (~4000x realtime per the bench, so a 1 s hop costs ~2.5 ms).
    - **energy endpointing**: with ``endpoint_silence`` set, a trailing
      silence of that many seconds after detected speech finalizes the
      utterance immediately instead of waiting for the chunk to fill.
    - **chunk completion**: a full ``chunk_seconds`` window always
      finalizes (the round-3 behavior).

    ``push`` RETURNS only finalized text: exactly the whole-buffered-
    window decode, never a partial hypothesis -- so concatenated push/
    flush output equals whole-chunk transcription and is never
    retracted.  Chunks are independent utterance windows (no
    cross-chunk decoder state): a word split across a boundary may be
    mis-recognized, the standard chunked-streaming trade-off.
    """

    def __init__(self, params, config: AsrConfig,
                 hop_seconds: float | None = None,
                 endpoint_silence: float | None = None,
                 endpoint_threshold: float = 0.01):
        self.params = params
        self.config = config
        rate = config.sample_rate
        self.chunk = int(rate * config.chunk_seconds)
        self.hop = int(rate * hop_seconds) if hop_seconds else None
        self.endpoint = int(rate * endpoint_silence) \
            if endpoint_silence else None
        self.endpoint_threshold = float(endpoint_threshold)
        self._pending = np.zeros((0,), dtype=np.float32)
        self._since_partial = 0
        self.partial_text = ""        # latest (revisable) hypothesis
        self.stable_text = ""         # agreed prefix of last two partials
        self.chunks_transcribed = 0
        self.partial_decodes = 0

    def _transcribe_one(self, chunk_samples: np.ndarray) -> str:
        tokens = transcribe(self.params, self.config,
                            jnp.asarray(chunk_samples[None]))
        self.chunks_transcribed += 1
        return decode_text(self.config, np.asarray(tokens)[0])

    def _reset_partial(self):
        self._since_partial = 0
        self.partial_text = ""
        self.stable_text = ""

    def _partial_decode(self):
        """Re-decode the buffered window (zero-padded: one compiled
        shape); keep the stable prefix = agreement with the previous
        hypothesis."""
        previous = self.partial_text
        hypothesis = self._transcribe_one(
            pad_audio(self.config, self._pending))
        self.chunks_transcribed -= 1          # partials are not chunks
        self.partial_decodes += 1
        agree = 0
        for a, b in zip(previous, hypothesis):
            if a != b:
                break
            agree += 1
        self.stable_text = hypothesis[:agree]
        self.partial_text = hypothesis
        self._since_partial = 0

    def _endpoint_reached(self) -> bool:
        """Speech followed by >= endpoint_silence of trailing quiet."""
        if self.endpoint is None \
                or len(self._pending) <= self.endpoint:
            return False
        tail = self._pending[-self.endpoint:]
        head = self._pending[:-self.endpoint]
        tail_rms = float(np.sqrt(np.mean(tail * tail)))
        head_peak = float(np.abs(head).max()) if len(head) else 0.0
        return (tail_rms < self.endpoint_threshold
                and head_peak >= self.endpoint_threshold)

    def push(self, samples) -> str:
        """Append samples; returns newly FINALIZED text ('' while the
        window fills -- watch ``partial_text``/``stable_text`` for the
        sub-chunk live hypothesis)."""
        samples = np.asarray(samples, dtype=np.float32).reshape(-1)
        self._pending = np.concatenate([self._pending, samples])
        self._since_partial += len(samples)
        emitted = []
        while len(self._pending) >= self.chunk:
            chunk, self._pending = (self._pending[:self.chunk],
                                    self._pending[self.chunk:])
            emitted.append(self._transcribe_one(chunk))
            self._reset_partial()
        if emitted:
            return "".join(emitted)
        if self._endpoint_reached():
            return self.flush()
        if self.hop and len(self._pending) \
                and self._since_partial >= self.hop:
            self._partial_decode()
        return ""

    def flush(self) -> str:
        """Finalize whatever partial window remains (zero-padded)."""
        if not len(self._pending):
            return ""
        tail, self._pending = self._pending, \
            np.zeros((0,), dtype=np.float32)
        self._reset_partial()
        return self._transcribe_one(pad_audio(self.config, tail))


def asr_loss(params: dict, config: AsrConfig, samples: jax.Array,
             targets: jax.Array) -> jax.Array:
    """Teacher-forced cross-entropy; targets [B, S] padded with PAD=259
    (ignored).  The training objective for fitting the ASR model."""
    c = config
    encoded = encode(params, c, log_mel(c, samples))
    bos = jnp.full((targets.shape[0], 1), c.bos_token, dtype=jnp.int32)
    inputs = jnp.concatenate([bos, targets[:, :-1]], axis=1)
    logits = _decode_states(params, c, inputs,
                            encoded).astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(log_probs, targets[..., None],
                                 axis=-1)[..., 0]
    mask = (targets != 259).astype(jnp.float32)
    return -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)
