"""Continuous batching for the LLM serving element (BASELINE config 3).

The reference's chat element forwards to an external Ollama server
(reference examples/llm/elements.py:92-212); here serving is native: a
slot-based continuous batcher owns a batched KV cache in HBM and a decode
loop on-device.

Design (the "hard part" flagged in SURVEY.md section 7): many actor
requests merge into device batches and de-multiplex back to per-request
token streams.

- ``max_slots`` sequences decode together as one [B] ``decode_step``;
- admission is CHUNKED and INTERLEAVED: prompt tokens are written
  chunk-at-a-time straight into the admitted slot's region of the
  batched cache (``llama.prefill_into_slot``; no scratch cache, no
  full-extent scatter), interleaved with decode ticks.  With
  ``decode_block == 1`` each ``step()`` prefills at most ONE
  ``prefill_chunk`` -- a long prompt never stalls active decodes beyond
  one chunk's latency.  With ``decode_block > 1`` (the pipelined path,
  below) a burst of admissions prefills one chunk PER admitting slot
  per step: the chunks are async dispatches chained on the cache, so a
  burst costs device time, not host round trips, and decode stall is
  bounded by one fused block's latency anyway;
- finished sequences (EOS or token budget) free their slot immediately;
  a long generation never blocks a short one (continuous, not static,
  batching);
- with ``decode_block > 1`` the decode loop is PIPELINED: the batcher
  keeps ``inflight`` fused blocks in flight, chaining each dispatch off
  the previous block's DEVICE-side carries (tokens/lengths/key/cache --
  ``llama.decode_block`` returns them) so the host never waits a tunnel
  round trip between dispatches; emitted tokens are copied back
  asynchronously and retired one block behind.  A request's tokens past
  its EOS/budget inside in-flight blocks are discarded host-side (the
  same overshoot semantics a single fused block already had);
- with ``decode_block_tokens > 0`` (ISSUE 8) generation is DEVICE
  RESIDENT: ``step()`` dispatches ``llama.decode_loop`` blocks -- a
  ``lax.while_loop`` with on-device sampling, per-slot stop detection
  (EOS + budget + cache boundary) and an emitted-token ring in the
  carry -- and the host pays ONE counted fetch per retired block (the
  ``fetch`` hook, wired to the pipeline's TransferLedger by the LLM
  element) instead of one round trip per token.  Admission and
  eviction happen only at block boundaries; ``speculative:
  ngram|draft`` layers multi-token decoding onto the loop with
  acceptance bookkeeping entirely on-device;
- with ``kv_page_tokens > 0`` the KV cache is PAGED (models/paged.py):
  slots borrow fixed-size pages from a shared pool as their sequences
  actually grow, a finished/evicted slot returns them, and a pool
  under pressure preempts the youngest slot (its generation resumes
  later from its committed tokens -- the same resume path
  :meth:`ContinuousBatcher.recover` uses after a device loss);
- the engine is synchronous and thread-agnostic: ``step()`` advances one
  tick and invokes per-request ``emit`` callbacks.  The serving element
  runs it on the event engine and pushes tokens to actor queues.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import llama
from .paged import PageAllocator, init_paged_cache, pages_per_slot
from .quant import draft_params
from ..utils.misc import next_power_of_two

__all__ = ["Request", "ContinuousBatcher", "MicroBatcher",
           "MicroBatchElement", "pad_to_bucket"]

# Batched admission advances at most this many slots per tick: compile
# buckets stay {1, 2, 4, 8} regardless of max_slots (an [8*chunk, dim]
# prefill matmul already feeds the MXU; wider bursts would only add
# power-of-two compile shapes, each a fresh jit of the full model).
_ADMISSION_BURST_MAX = 8

# ``speculative: auto`` enables draft speculation only when the startup
# micro-probe measures at least this tokens/s ratio over plain decode.
SPEC_AUTO_MIN_RATIO = 1.2
# Probe shape: warmup block (compile, off the clock) + timed blocks per
# arm, best-of so a GC hiccup cannot flip the verdict.
_SPEC_PROBE_BLOCKS = 3


def _knob_on(value, default: bool) -> bool:
    """on/off|true/false|bool -> bool, the same normalization the
    create-time domain check applies to choice parameters."""
    if isinstance(value, bool):
        return value
    text = str(value).strip().lower()
    if not text:
        return default
    return text in ("on", "true", "1", "yes")


def pad_to_bucket(rows: list) -> list:
    """Pad a ragged admission burst to its power-of-two compile bucket
    by repeating the first row -- idempotent device work (same inputs
    recompute the same values), no uninitialized rows, at most doubles
    a ragged batch.  Shared by the ContinuousBatcher's batched prefill
    and every MicroBatcher dispatch."""
    bucket = next_power_of_two(len(rows))
    return list(rows) + [rows[0]] * (bucket - len(rows))


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_tokens: list[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    eos_tokens: tuple = ()
    emit: Callable | None = None     # fn(request_id, token_id, finished)
    # runtime state
    slot: int = -1
    prefill_pos: int = 0             # prompt tokens already written
    generated: int = 0
    done: bool = False
    # resume state: the submitted prompt, every token emitted so far,
    # and how many of those have been folded back into prompt_tokens
    # (recover()/page-pool preemption re-prefill prompt + committed and
    # keep generating -- already-delivered tokens are never re-emitted,
    # and ``rebased`` keeps the budget/boundary arithmetic honest).
    base_prompt: list = dataclasses.field(default_factory=list)
    committed: list = dataclasses.field(default_factory=list)
    rebased: int = 0
    admit_seq: int = -1              # admission order (eviction picks
    #                                  the youngest victim)
    submit_time: float = 0.0         # llm_ttft_ms / llm_tpot_ms stamps
    first_time: float = 0.0
    # Unified QoS admission (ISSUE 12, gateway/qos.py): the owning
    # frame's tenant/class, and the pre-computed class rank slot
    # admission sorts by (lower = more urgent; equal ranks keep
    # submission order, so the default 0 everywhere is exactly the
    # old FIFO).  Plane 4 of the one-scheduler refactor: the batcher
    # admits by the same class vocabulary as the stage credits.
    tenant: str | None = None
    qos_class: str | None = None
    qos_rank: int = 0


_select_tokens = jax.jit(llama.select_tokens,
                         static_argnames=("top_k",))


class _InflightBlock:
    """One dispatched-but-unretired fused decode block."""
    __slots__ = ("emitted", "snapshot", "firsts", "steps")

    def __init__(self, emitted, snapshot, firsts, steps):
        self.emitted = emitted        # [steps, B] device, copy in flight
        self.snapshot = snapshot      # [(slot, request)] active at dispatch
        # ([(slot, request)], stacked first-token device array) or None:
        # admissions folded into this block, fetched in ONE host copy.
        self.firsts = firsts
        self.steps = steps


class _LoopBlock:
    """One dispatched-but-unretired device-resident generation block
    (llama.decode_loop).  ``tree`` holds every device array the retire
    needs -- emitted ring, counts, carries, accept counters, folded
    first tokens -- fetched in ONE counted host copy."""
    __slots__ = ("tree", "snapshot", "firsts_meta")

    def __init__(self, tree, snapshot, firsts_meta):
        self.tree = tree
        self.snapshot = snapshot      # [(slot, request)] in the block
        self.firsts_meta = firsts_meta  # [(slot, request)] admissions


class ContinuousBatcher:
    def __init__(self, params, config: llama.LlamaConfig,
                 max_slots: int = 8, max_seq: int | None = None,
                 prefill_chunk: int = 512, rng_seed: int = 0,
                 decode_block: int = 1, inflight: int = 2,
                 cache_put: Callable | None = None,
                 decode_block_tokens: int = 0,
                 speculative: str = "off", spec_tokens: int = 4,
                 spec_window: int = 32, kv_page_tokens: int = 0,
                 kv_pages: int | None = None,
                 fetch: Callable | None = None,
                 fault_probe: Callable | None = None,
                 on_block: Callable | None = None,
                 sample_top_k: int = 0,
                 prefix_cache: bool | str = False,
                 prefix_min_tokens: int = 64,
                 spec_autoprobe: bool | str = True):
        self.params = params
        # A pre-sharded (TP/fsdp) quantized tree must keep XLA's
        # matmul path -- resolved here, where the concrete leaves'
        # sharding is visible (llama._matmul_safe_config).
        self.config = llama._matmul_safe_config(config, params)
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq
        self.prefill_chunk = min(prefill_chunk, self.max_seq)
        # >1: fuse that many decode iterations (sampling included) into
        # one device dispatch -- the host round trip stops bounding
        # tokens/s.  Tokens a request emits past its EOS/budget inside a
        # block are discarded host-side.
        self.decode_block = max(1, int(decode_block))
        # How many fused blocks to keep in flight (decode_block > 1
        # only).  Each dispatch chains off the previous block's device
        # carries, so depth d hides up to d * block_compute of host
        # round-trip latency behind device work.
        self.inflight = max(1, int(inflight))
        # Device-resident generation (ISSUE 8): > 0 sizes the emitted
        # ring of llama.decode_loop blocks -- sampling, stop detection
        # and (optionally) speculation run inside one dispatch, the
        # host fetches once per block.  Supersedes decode_block when
        # set.
        self.decode_block_tokens = max(0, int(decode_block_tokens))
        self.device_loop = self.decode_block_tokens > 0
        # Normalized exactly as the create-time domain check
        # (analysis/params.py _check_value) normalizes, so a value
        # that passes preflight cannot fail here on case/whitespace.
        self.speculative = str(speculative or "off").strip().lower()
        if self.speculative not in ("off", "ngram", "draft", "auto"):
            raise ValueError(f"speculative={speculative!r}: one of "
                             f"off|ngram|draft|auto")
        # ``auto`` (ISSUE 18): measure draft speculation against plain
        # decode in a startup micro-probe and enable it only on a
        # >= SPEC_AUTO_MIN_RATIO win -- auto never raises and never
        # enables a losing config, so configs explicit ``draft`` would
        # refuse (no device loop, ring too small) just resolve to off.
        self.spec_autoprobe = _knob_on(spec_autoprobe, default=True)
        self.spec_probe_ratio = 0.0
        if self.speculative == "auto" and (
                not self.device_loop
                or self.decode_block_tokens < max(1, int(spec_tokens)) + 1
                or not self.spec_autoprobe):
            self.speculative = "off"
        if self.speculative != "off" and not self.device_loop:
            raise ValueError(
                "speculative decoding rides the device loop: set "
                "decode_block_tokens > 0")
        self.spec_tokens = max(1, int(spec_tokens))
        if self.speculative != "off" \
                and self.decode_block_tokens < self.spec_tokens + 1:
            # The loop's room test needs one worst-case speculative
            # emission (spec_tokens + 1) to fit the ring; a smaller
            # ring would dispatch blocks that run ZERO iterations --
            # a silent no-progress wedge, so refuse it up front.
            raise ValueError(
                f"decode_block_tokens={self.decode_block_tokens} "
                f"cannot hold one speculative emission (spec_tokens + "
                f"1 = {self.spec_tokens + 1}); raise the ring or "
                f"lower spec_tokens")
        self.spec_window = max(4, int(spec_window))
        # Restrict sampled rows to the k highest logits (0 = full
        # categorical).  Static per-trace: rides llama.select_tokens /
        # decode_loop / decode_block through the ops top-k interface
        # (the Pallas kernel on TPU, lax.top_k elsewhere); greedy rows
        # are unaffected either way.  Bounded at build to the kernel's
        # lane cap so a CPU-tested config cannot blow up mid-serving
        # on TPU (the create-time domain check mirrors this bound).
        self.sample_top_k = max(0, int(sample_top_k))
        if self.sample_top_k > 128:
            raise ValueError(
                f"sample_top_k={self.sample_top_k}: the on-TPU top-k "
                f"kernel holds candidates in one 128-lane tile; use "
                f"k <= 128 (0 = full-vocab categorical)")
        self._draft = draft_params(params) \
            if self.speculative == "draft" else None
        # Paged KV cache (models/paged.py): fixed-size pages + per-slot
        # page table; 0 keeps the monolithic [slots, max_seq] cache.
        self.kv_page_tokens = max(0, int(kv_page_tokens))
        # Shared-prefix page cache (ISSUE 18): requests whose prompts
        # share leading pages map ONE physical copy, refcounted, and
        # skip prefill over the shared span.  Rides the page table, so
        # it requires the paged cache.
        self.prefix_cache = _knob_on(prefix_cache, default=False)
        self.prefix_min_tokens = max(1, int(prefix_min_tokens))
        if self.prefix_cache and not self.kv_page_tokens:
            raise ValueError(
                "prefix_cache: on shares KV at page granularity: set "
                "kv_page_tokens > 0")
        self._pages: PageAllocator | None = None
        if self.kv_page_tokens:
            pps = pages_per_slot(self.max_seq, self.kv_page_tokens)
            if self.prefill_chunk % self.kv_page_tokens:
                raise ValueError(
                    f"kv_page_tokens={self.kv_page_tokens} must divide "
                    f"prefill_chunk ({self.prefill_chunk}) so admission "
                    f"chunks stay page-aligned")
            self.cache = init_paged_cache(
                config, max_slots, self.max_seq, self.kv_page_tokens,
                kv_pages)
            pool = llama.cache_array(self.cache).shape[1]
            self._pages = PageAllocator(
                pool, pps, max_slots, prefix_cache=self.prefix_cache,
                prefix_min_tokens=self.prefix_min_tokens)
        else:
            self.cache = llama.init_cache(config, max_slots, self.max_seq)
        # Multichip serving: ``cache_put`` places the initial KV cache
        # onto the serving mesh (e.g. ``lambda c: plan.put(c,
        # llama.cache_specs(config))`` for TP-sharded kv heads) --
        # donation keeps that sharding across every subsequent dispatch,
        # so one placement at init is enough.  Params are pre-sharded by
        # the caller the same way (quantized trees via
        # quant.quantize_specs).
        self._cache_put = cache_put
        if cache_put is not None:
            self.cache = cache_put(self.cache)
        # One explicit host fetch per retired device-loop block; the
        # LLM element wires the pipeline TransferLedger's counted fetch
        # here so serving obeys the device-resident swag contract.
        self._fetch = fetch if fetch is not None else jax.device_get
        # Armed-chaos probe called before every device-loop block
        # dispatch (the ``decode_block`` injection point); None = cold.
        self._fault_probe = fault_probe
        # Flight-recorder tap (ISSUE 10): ``on_block("dispatch" |
        # "retire", occupied_slots)`` fires at every fused/loop block
        # boundary.  The LLM element wires it to the pipeline's
        # recorder so serving cadence shows up on the same timeline as
        # the frames it serves; None (the default) costs one branch.
        self.on_block = on_block
        self.lengths = np.zeros(max_slots, dtype=np.int32)
        self.current = np.zeros(max_slots, dtype=np.int32)
        self.temperatures = np.zeros(max_slots, dtype=np.float32)
        self.decoding = np.zeros(max_slots, dtype=bool)
        self.slots: list[Request | None] = [None] * max_slots
        self.pending: list[Request] = []
        self._prefilling: list[int] = []      # slot FIFO, round-robin
        self._key = jax.random.PRNGKey(rng_seed)
        # pipelining state (decode_block > 1): device-side carries of
        # the latest dispatched block, cached device mirrors of the
        # active/temperature rows (re-uploaded only when they change),
        # first-token futures from prefill completions not yet folded
        # into a dispatch, and the in-flight block queue.
        self._chain: tuple | None = None      # (tokens_dev, lengths_dev)
        self._active_dev = None
        self._temps_dev = None
        self._pending_first: dict[int, tuple] = {}   # slot -> (req, dev)
        self._inflight: deque[_InflightBlock] = deque()
        # device-loop state: the chained carries of the latest loop
        # block, the in-flight loop-block queue, host mirrors of
        # per-slot eos rows and a conservative length upper bound for
        # page allocation while blocks are in flight.
        self._loop_chain: dict | None = None
        self._loop_inflight: deque[_LoopBlock] = deque()
        self._eos_width = 1
        self._eos_rows = np.full((max_slots, 1), -1, dtype=np.int32)
        self._lengths_upper = np.zeros(max_slots, dtype=np.int32)
        self._admit_seq = 0
        # Slots whose chained ``active`` flag must drop at the next
        # dispatch (host-side finish/cancel/eviction the device hasn't
        # seen yet).
        self._force_inactive: set[int] = set()
        # perf counters
        self.tokens_emitted = 0
        self.steps = 0
        self.prefill_tokens = 0
        self.blocks_dispatched = 0
        self.blocks_retired = 0
        self.accepted_tokens = 0
        self.draft_tokens = 0
        self.evictions = 0
        self.recoveries = 0
        # prefix-cache accounting (ISSUE 18): prompt tokens admission
        # skipped because their pages were adopted from the index.
        self.prefix_shared_tokens = 0
        # per-request latency stamps drained by the serving element
        # into the telemetry plane (llm_ttft_ms / llm_tpot_ms).
        self._request_stats: list[dict] = []
        # ``speculative: auto``: measure, then commit to draft or off.
        if self.speculative == "auto":
            self.spec_probe_ratio = self._spec_probe()
            if self.spec_probe_ratio >= SPEC_AUTO_MIN_RATIO:
                self.speculative = "draft"
                self._draft = draft_params(params)
            else:
                self.speculative = "off"

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request):
        if len(request.prompt_tokens) >= self.max_seq:
            request.prompt_tokens = \
                request.prompt_tokens[-(self.max_seq // 2):]
        # An empty prompt still needs one position of context to sample
        # from; condition it on a single pad token rather than indexing
        # into uninitialised padding.
        if not request.prompt_tokens:
            request.prompt_tokens = [0]
        request.base_prompt = list(request.prompt_tokens)
        request.submit_time = time.perf_counter()
        self.pending.append(request)

    def _next_pending(self) -> Request:
        """Pop the next request to admit: the best ``qos_rank`` (ISSUE
        12 -- the batcher is the fourth admission plane the unified
        scheduler reaches), queue position breaking ties so the
        all-default case is EXACTLY the old FIFO and an evicted
        request's front re-insert still wins its class."""
        best = min(range(len(self.pending)),
                   key=lambda index: (self.pending[index].qos_rank,
                                      index))
        return self.pending.pop(best)

    def _admit(self):
        """Assign free slots to pending requests (no device work: the
        prompt is written chunk-at-a-time by ``_prefill_tick``)."""
        for slot, occupant in enumerate(self.slots):
            if occupant is not None or not self.pending:
                continue
            request = self._next_pending()
            request.slot = slot
            request.prefill_pos = 0
            if self._pages is not None and self.prefix_cache:
                # Shared-prefix adoption (ISSUE 18): map the longest
                # indexed page chain matching this prompt read-only
                # and start prefill past it -- the skipped span never
                # touches the device.
                shared = self._pages.adopt_prefix(
                    slot, request.prompt_tokens, self.kv_page_tokens)
                if shared:
                    request.prefill_pos = shared
                    self.prefix_shared_tokens += shared
            request.admit_seq = self._admit_seq
            self._admit_seq += 1
            self.slots[slot] = request
            self.lengths[slot] = 0
            self._lengths_upper[slot] = 0
            self.current[slot] = 0
            self.temperatures[slot] = request.temperature
            self._temps_dev = None
            self.decoding[slot] = False
            self._set_eos_row(slot, request.eos_tokens)
            self._prefilling.append(slot)

    def _set_eos_row(self, slot: int, eos_tokens) -> None:
        """Mirror one slot's stop-token set into the host eos table
        (uploaded with every device-loop dispatch; -1 pads never match
        a real token id).  A wider set than any seen before grows the
        table -- a new compile shape, once per distinct width."""
        width = max(1, len(eos_tokens or ()))
        if width > self._eos_width:
            grown = np.full((self.max_slots, width), -1, dtype=np.int32)
            grown[:, :self._eos_width] = self._eos_rows
            self._eos_rows = grown
            self._eos_width = width
        self._eos_rows[slot] = -1
        for column, token in enumerate(eos_tokens or ()):
            self._eos_rows[slot, column] = int(token)

    def _prefill_tick(self):
        """Advance admissions by one chunk (<= prefill_chunk tokens)
        each.  Pipelined path (decode_block > 1): every admitting slot
        advances -- a multi-slot burst runs as ONE batched dispatch
        (``llama.prefill_into_slots``: the [N*S, dim] matmuls feed the
        MXU far better than N serialized [S, dim] dispatches), falling
        back to per-slot dispatches for the flash-attention config.
        Synchronous path (decode_block == 1): at most ONE chunk total,
        preserving the one-chunk decode-stall bound (each chunk's
        completion fetch blocks the host there)."""
        pipelined = self.decode_block > 1 or self.device_loop
        if (pipelined and len(self._prefilling) > 1
                and self.config.attention != "flash"):
            self._prefill_tick_batched()
            return
        budget = len(self._prefilling) if pipelined \
            else min(1, len(self._prefilling))
        for _ in range(budget):
            if not self._prefilling:
                break           # shrunk by a pressure eviction below
            slot = self._prefilling.pop(0)
            request = self.slots[slot]
            if request is None:     # cancelled/evicted while waiting
                continue
            start, chunk_tokens = self._admission_chunk(request)
            if not self._ensure_pages(slot, start + self.prefill_chunk):
                self._prefilling.append(slot)   # pool pressure: wait
                continue
            self._sync_page_table()
            padded = np.zeros((1, self.prefill_chunk), dtype=np.int32)
            padded[0, :len(chunk_tokens)] = chunk_tokens
            logits, self.cache = llama.prefill_into_slot(
                self.params, self.config, jnp.asarray(padded),
                self.cache, jnp.int32(slot), jnp.int32(start))
            self._admission_advance(slot, request, start,
                                    len(chunk_tokens), logits)

    def _prefill_tick_batched(self):
        """One chunk for EVERY admitting slot in a single batched
        dispatch.  N is padded up to a power-of-two compile bucket by
        duplicating the first row (idempotent: same slot, same start,
        same tokens -- see llama.prefill_into_slots)."""
        admitting = []
        for _ in range(len(self._prefilling)):
            if not self._prefilling:
                break           # shrunk by a pressure eviction below
            slot = self._prefilling.pop(0)
            if self.slots[slot] is None:    # cancelled/evicted
                continue
            start, _ = self._admission_chunk(self.slots[slot])
            if not self._ensure_pages(slot, start + self.prefill_chunk):
                self._prefilling.append(slot)   # pool pressure: wait
                continue
            admitting.append(slot)
        # A LATER slot's ensure may have preempted an EARLIER admitted
        # one for its pages: drop evicted slots before dispatching.
        admitting = [s for s in admitting if self.slots[s] is not None]
        # Overflow waits one tick (FIFO rotation keeps chunk fairness);
        # see _ADMISSION_BURST_MAX for why the burst is capped.
        self._prefilling.extend(admitting[_ADMISSION_BURST_MAX:])
        admitting = admitting[:_ADMISSION_BURST_MAX]
        if not admitting:
            return
        self._sync_page_table()
        n = len(admitting)
        rows = pad_to_bucket(admitting)
        bucket = len(rows)
        tokens = np.zeros((bucket, self.prefill_chunk), dtype=np.int32)
        slot_rows = np.zeros(bucket, dtype=np.int32)
        starts = np.zeros(bucket, dtype=np.int32)
        metas = []
        for i, slot in enumerate(rows):
            request = self.slots[slot]
            start, chunk_tokens = self._admission_chunk(request)
            tokens[i, :len(chunk_tokens)] = chunk_tokens
            slot_rows[i] = slot
            starts[i] = start
            metas.append((slot, request, start, len(chunk_tokens)))
        logits, self.cache = llama.prefill_into_slots(
            self.params, self.config, jnp.asarray(tokens), self.cache,
            jnp.asarray(slot_rows), jnp.asarray(starts))
        for i, (slot, request, start, chunk_len) in enumerate(metas[:n]):
            self._admission_advance(slot, request, start, chunk_len,
                                    logits[i:i + 1])

    def _admission_chunk(self, request: Request):
        """(start, chunk tokens) of the request's next prefill chunk.
        The write start clamps so a full chunk always fits inside the
        cache (a spilling dynamic_update_slice would clamp internally
        and corrupt earlier positions); a clamped start re-writes the
        overlap with byte-identical KV (same tokens, same positions), so
        correctness is unaffected and only the final chunk pays.  The
        chunk is always PADDED to prefill_chunk by the caller: one
        compiled shape per admission; pad positions hold garbage KV, but
        decode writes each position before the length mask ever admits
        it, and the causal prefill mask never looks past the query
        position."""
        start = min(request.prefill_pos,
                    self.max_seq - self.prefill_chunk)
        return start, request.prompt_tokens[
            start:start + self.prefill_chunk]

    def _admission_advance(self, slot: int, request: Request,
                           start: int, chunk_len: int, logits):
        """Account one written chunk; on the FINAL chunk, sample the
        first generated token from the last real prompt position's
        logits ([1, S, vocab] row) and hand the slot to decode --
        without fetching on the pipelined path (the device scalar folds
        into the next block dispatch and emits when that block
        retires)."""
        prompt = request.prompt_tokens
        self.prefill_tokens += start + chunk_len - request.prefill_pos
        request.prefill_pos = start + chunk_len
        if self._pages is not None and self.prefix_cache:
            # Index every whole prompt page now written: the content
            # is position-deterministic, so the pages can serve any
            # later prompt sharing this prefix (register as we go --
            # even a mid-admission chain is adoptable).
            self._pages.register_prefix(slot, prompt,
                                        request.prefill_pos,
                                        self.kv_page_tokens)
        if request.prefill_pos < len(prompt):
            self._prefilling.append(slot)       # more chunks to go
            return
        last = len(prompt) - start - 1
        first = self._sample(logits[:, last, :], request.temperature)
        self.lengths[slot] = len(prompt)
        self._lengths_upper[slot] = len(prompt)
        self.decoding[slot] = True
        self._active_dev = None
        if self.device_loop or self.decode_block > 1:
            # No host copy here: the retire fetches the CONCATENATED
            # firsts array of the block this admission folds into.
            self._pending_first[slot] = (request, first)
        else:
            first_token = int(jax.device_get(first)[0])
            self.current[slot] = first_token
            self._emit(request, first_token)

    # -- decode ------------------------------------------------------------

    def _sample(self, logits, temperature: float):
        if temperature and temperature > 0:
            self._key, sub = jax.random.split(self._key)
            return llama.temperature_sample(sub, logits, temperature)
        return llama.greedy_sample(logits)

    def step(self) -> int:
        """Admit pending requests, advance one prefill chunk per
        admitting slot, dispatch/retire decode work across all
        generating slots, emit tokens.  Returns the number of occupied
        slots (prefilling + decoding)."""
        self._admit()
        self._prefill_tick()
        decoding = [i for i in range(self.max_slots) if self.decoding[i]]
        if self.device_loop:
            if decoding or self._pending_first or self._loop_inflight:
                while len(self._loop_inflight) < self.inflight:
                    if not self._dispatch_loop_block():
                        break
                if self._loop_inflight:
                    self._retire_loop_block()
            return sum(1 for r in self.slots if r is not None)
        if self.decode_block > 1:
            if decoding:
                # Top the pipeline up to `inflight` blocks, then retire
                # the oldest: steady state is one dispatch + one retire
                # per step, with the retire's host copy overlapping the
                # newer blocks' device compute.  Stop early once the
                # outstanding blocks already cover every active
                # request's remaining budget (EOS can still cut a
                # stream shorter; that overshoot is discarded).
                remaining = max(
                    self.slots[i].max_new_tokens - self.slots[i].generated
                    for i in decoding if self.slots[i] is not None)
                while (len(self._inflight) < self.inflight
                       and len(self._inflight) * self.decode_block
                       < remaining):
                    if self._dispatch_block(decoding) is False:
                        break
            if self._inflight:
                self._retire_block()
        elif decoding:
            self._decode_tick(decoding)
        return sum(1 for r in self.slots if r is not None)

    def _decode_tick(self, decoding: list[int]):
        if self._pages is not None:
            for slot in decoding:
                if not self._ensure_pages(slot,
                                          int(self.lengths[slot]) + 2):
                    # Unreachable while the pool holds one full slot
                    # (pps + 1, enforced at init): preempt the slot
                    # itself rather than let its write land on the
                    # trash page (it resumes from committed tokens).
                    self._evict_slot(slot)
            self._sync_page_table()
            # An ensure may have preempted another decoding slot:
            # refresh the list (and the write mask reads the flags).
            decoding = [i for i in decoding if self.decoding[i]]
            if not decoding:
                return
        tokens = jnp.asarray(self.current)
        # Rows not decoding (empty or mid-prefill) still flow through the
        # batched step; route their KV write to the trash position
        # max_seq-1, which real content never occupies (decode finishes
        # at lengths >= max_seq-1, so its last write is max_seq-2, and
        # the masks never admit max_seq-1 for a live row).
        write_positions = np.where(self.decoding, self.lengths,
                                   self.max_seq - 1).astype(np.int32)
        logits, self.cache = llama.decode_step(
            self.params, self.config, tokens, self.cache,
            jnp.asarray(write_positions))
        self._key, sub = jax.random.split(self._key)
        next_tokens = np.asarray(jax.device_get(_select_tokens(
            sub, logits, jnp.asarray(self.temperatures),
            top_k=self.sample_top_k)), dtype=np.int32)
        self.steps += 1
        for i in decoding:
            request = self.slots[i]
            if request is None:                 # freed mid-dispatch
                continue
            self.lengths[i] += 1
            token = int(next_tokens[i])
            self.current[i] = token
            self._emit(request, token)

    def _dispatch_block(self, decoding: list[int]):
        """Enqueue one fused decode block chained off the previous
        block's device carries.  No host synchronization: tokens and
        lengths come from the chain (with prefill-completion overrides
        applied on device), the key chains through the kernel, and the
        emitted tokens start copying to the host asynchronously."""
        if self._pages is not None:
            for slot in decoding:
                if not self._ensure_pages(
                        slot, int(self._lengths_upper[slot])
                        + self.decode_block + 1):
                    return False        # retire in-flight blocks first
            self._sync_page_table()
        if self._chain is None:
            tokens = jnp.asarray(self.current)
            lengths = jnp.asarray(self.lengths)
        else:
            tokens, lengths = self._chain
        first_meta, first_vals = [], []
        for slot in sorted(self._pending_first):
            request, first = self._pending_first[slot]
            tokens = tokens.at[slot].set(first[0])
            lengths = lengths.at[slot].set(len(request.prompt_tokens))
            first_meta.append((slot, request))
            first_vals.append(first)
        self._pending_first.clear()
        if first_vals:
            # ONE device array for all admissions folded into this
            # block: the retire then pays a single host fetch instead of
            # one round trip per admitted request (8 sequential tiny
            # fetches cost ~8 RTTs through the tunnel).
            firsts_dev = jnp.concatenate(first_vals)
            firsts_dev.copy_to_host_async()
            firsts = (first_meta, firsts_dev)
        else:
            firsts = None
        if self._active_dev is None:
            self._active_dev = jnp.asarray(self.decoding)
        if self._temps_dev is None:
            self._temps_dev = jnp.asarray(self.temperatures)
        emitted, tokens_n, lengths_n, self._key, self.cache = \
            llama.decode_block(
                self.params, self.config, tokens, self.cache, lengths,
                self._active_dev, self._temps_dev, self._key,
                num_steps=self.decode_block,
                top_k=self.sample_top_k)
        emitted.copy_to_host_async()
        self._chain = (tokens_n, lengths_n)
        for i in decoding:                      # host mirror (clamped)
            self.lengths[i] = min(self.lengths[i] + self.decode_block,
                                  self.max_seq - 1)
        for i in decoding:
            self._lengths_upper[i] = min(
                int(self._lengths_upper[i]) + self.decode_block,
                self.max_seq)
        self._inflight.append(_InflightBlock(
            emitted, [(i, self.slots[i]) for i in decoding], firsts,
            self.decode_block))
        if self.on_block is not None:
            self.on_block("dispatch", len(decoding))

    def _retire_block(self):
        """Fetch the OLDEST in-flight block's tokens (the async copy
        has been overlapping newer blocks' compute) and de-multiplex
        host-side, truncating each request at its EOS/budget (overshoot
        KV lands beyond the freed slot's next occupant's length mask,
        so it is never read).  A slot freed and re-admitted while this
        block was in flight is skipped via the request snapshot."""
        blk = self._inflight.popleft()
        emitted = np.asarray(blk.emitted)       # [steps, B]
        self.steps += 1
        if self.on_block is not None:
            self.on_block("retire", len(blk.snapshot))
        if blk.firsts is not None:
            first_meta, firsts_dev = blk.firsts
            first_tokens = np.asarray(firsts_dev)    # one fetch for all
            for (slot, request), token in zip(first_meta, first_tokens):
                if self.slots[slot] is request and not request.done:
                    token = int(token)
                    self.current[slot] = token
                    self._emit(request, token)
        for slot, request in blk.snapshot:
            if request is None or self.slots[slot] is not request:
                continue
            for block_step in range(blk.steps):
                if self.slots[slot] is not request:     # finished
                    break
                token = int(emitted[block_step, slot])
                self.current[slot] = token
                self._emit(request, token)

    # -- speculative auto-probe (ISSUE 18) ---------------------------------

    def _spec_probe(self) -> float:
        """Measure draft speculation against plain decode on a SCRATCH
        cache (identical shapes to serving; ``self.cache`` is never
        touched) and return spec tokens/s over plain tokens/s.  Each
        arm pays one warmup block for compile, then the best of
        ``_SPEC_PROBE_BLOCKS`` timed blocks counts -- a host hiccup on
        one block must not flip the verdict."""
        ring = self.decode_block_tokens
        draft = draft_params(self.params)
        tokens = jnp.zeros(self.max_slots, dtype=jnp.int32)
        lengths = jnp.full(self.max_slots, self.max_seq // 2,
                           dtype=jnp.int32)
        active = jnp.ones(self.max_slots, dtype=bool)
        temps = jnp.zeros(self.max_slots, dtype=jnp.float32)
        eos = jnp.full((self.max_slots, 1), -1, dtype=jnp.int32)
        history = jnp.full((self.max_slots, 1), -1, dtype=jnp.int32)
        rates = {}
        for mode, dparams in (("off", None), ("draft", draft)):
            cache = self._probe_cache()
            key = jax.random.PRNGKey(0)
            best = 0.0
            for index in range(_SPEC_PROBE_BLOCKS + 1):
                budget = jnp.full(self.max_slots, ring,
                                  dtype=jnp.int32)
                begin = time.perf_counter()
                (_, counts, tokens, _, _, _, history, key, _, _, _,
                 cache) = llama.decode_loop(
                    self.params, self.config, tokens, cache, lengths,
                    active, budget, temps, eos, history, key,
                    ring=ring, speculative=mode,
                    spec_tokens=self.spec_tokens,
                    spec_window=self.spec_window, draft=dparams,
                    top_k=self.sample_top_k)
                emitted = int(np.asarray(jax.device_get(counts)).sum())
                elapsed = time.perf_counter() - begin
                if index and elapsed > 0:       # block 0 = compile
                    best = max(best, emitted / elapsed)
            rates[mode] = best
        return rates["draft"] / rates["off"] if rates["off"] else 0.0

    def _probe_cache(self):
        """A scratch serving cache for the probe.  Paged configs get a
        fully-mapped table (each slot's logical pages spread over the
        pool) so the probe pays real gather/scatter traffic instead of
        the all-trash-page fast case."""
        if not self.kv_page_tokens:
            cache = llama.init_cache(self.config, self.max_slots,
                                     self.max_seq)
        else:
            cache = init_paged_cache(
                self.config, self.max_slots, self.max_seq,
                self.kv_page_tokens, self._pages.total)
            pps = self._pages.pps
            table = (np.arange(self.max_slots * pps, dtype=np.int32)
                     % max(1, self._pages.total - 1)) + 1
            cache["page_table"] = jnp.asarray(
                table.reshape(self.max_slots, pps))
        if self._cache_put is not None:
            cache = self._cache_put(cache)
        return cache

    # -- device-resident generation loop (ISSUE 8) -------------------------

    def _host_state(self):
        """Fresh device carries from the host mirrors (first dispatch
        and post-recover; every later block chains device-side)."""
        self._key, loop_key = jax.random.split(self._key)
        history_width = self.spec_window \
            if self.speculative == "ngram" else 1
        return {
            "tokens": jnp.asarray(self.current),
            "lengths": jnp.asarray(self.lengths),
            "active": jnp.zeros(self.max_slots, dtype=bool),
            "budget": jnp.zeros(self.max_slots, dtype=jnp.int32),
            "history": jnp.full((self.max_slots, history_width), -1,
                                dtype=jnp.int32),
            "key": loop_key,
        }

    def _dispatch_loop_block(self) -> bool:
        """Chain one llama.decode_loop block off the previous block's
        device carries, folding completed admissions in (their first
        token, budget, stop set and draft history ride device-side --
        no host round trip).  Returns False when there is nothing to
        decode, outstanding blocks already cover every request's
        budget, or page-pool pressure wants the in-flight blocks
        retired before an eviction can free room."""
        ring = self.decode_block_tokens
        spec_extra = self.spec_tokens + 1 \
            if self.speculative != "off" else 1
        live = [i for i in range(self.max_slots) if self.decoding[i]]
        joining = sorted(self._pending_first)
        if not live and not joining:
            return False
        if not joining and self._loop_inflight:
            # Outstanding blocks already cover every live request's
            # remaining budget (EOS may cut a row shorter -- the loop's
            # own stop detection idles it, so overshoot blocks cost
            # almost nothing device-side).
            remaining = max(
                (self.slots[i].max_new_tokens - self.slots[i].generated
                 for i in live if self.slots[i] is not None), default=0)
            if len(self._loop_inflight) * ring >= remaining:
                return False
        for slot in sorted({*live, *joining}):
            if self.slots[slot] is None:
                continue                # evicted by an earlier ensure
            upto = int(self._lengths_upper[slot]) + ring + spec_extra
            if not self._ensure_pages(slot, upto):
                return False            # retire in-flight blocks first
        # An ensure above may have PREEMPTED a just-admitted slot for
        # its pages (the youngest occupant is usually a joining one):
        # re-snapshot both lists so the fold-in below never touches an
        # evicted slot's popped _pending_first entry.
        live = [i for i in range(self.max_slots) if self.decoding[i]]
        joining = sorted(self._pending_first)
        if not live and not joining:
            return False
        if self._fault_probe is not None:
            self._fault_probe("decode_block")
        state = self._loop_chain or self._host_state()
        tokens, lengths = state["tokens"], state["lengths"]
        active, budget = state["active"], state["budget"]
        history, key = state["history"], state["key"]
        for slot in self._force_inactive:
            active = active.at[slot].set(False)
        self._force_inactive.clear()
        eos_dev = jnp.asarray(self._eos_rows)
        temps_dev = jnp.asarray(self.temperatures)
        firsts_meta, first_vals = [], []
        for slot in joining:
            request, first = self._pending_first.pop(slot)
            plen = len(request.prompt_tokens)
            tokens = tokens.at[slot].set(first[0])
            lengths = lengths.at[slot].set(plen)
            budget = budget.at[slot].set(
                request.max_new_tokens - request.generated - 1)
            # The slot decodes on unless its FIRST token already
            # finishes it; the EOS part of that verdict folds in
            # device-side (the first token is an unfetched scalar).
            if (request.max_new_tokens - request.generated > 1
                    and plen + 1 < self.max_seq):
                active = active.at[slot].set(
                    jnp.logical_not((first[0] == eos_dev[slot]).any()))
            else:
                active = active.at[slot].set(False)
            if self.speculative == "ngram":
                tail = np.full(self.spec_window, -1, dtype=np.int32)
                recent = request.prompt_tokens[-self.spec_window:]
                tail[len(tail) - len(recent):] = recent
                history = history.at[slot].set(jnp.asarray(tail))
            firsts_meta.append((slot, request))
            first_vals.append(first)
        self._sync_page_table()
        (emitted, counts, tokens_next, lengths_next, active_next,
         budget_next, history_next, key_next, accepted, drafted, steps,
         self.cache) = llama.decode_loop(
            self.params, self.config, tokens, self.cache, lengths,
            active, budget, temps_dev, eos_dev, history, key,
            ring=ring, speculative=self.speculative,
            spec_tokens=self.spec_tokens,
            spec_window=self.spec_window, draft=self._draft,
            top_k=self.sample_top_k)
        # Only what the retire actually reads rides the counted fetch
        # (the active/budget/history carries chain device-side).
        tree = {"emitted": emitted, "counts": counts,
                "lengths": lengths_next,
                "accepted": accepted, "drafted": drafted, "steps": steps}
        if first_vals:
            tree["firsts"] = jnp.concatenate(first_vals)
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()   # overlap newer blocks
        self._loop_chain = {"tokens": tokens_next,
                            "lengths": lengths_next,
                            "active": active_next, "budget": budget_next,
                            "history": history_next, "key": key_next}
        snapshot = sorted({*live, *joining})
        for slot in snapshot:
            self._lengths_upper[slot] = min(
                int(self._lengths_upper[slot]) + ring, self.max_seq)
        self._loop_inflight.append(_LoopBlock(
            tree, [(i, self.slots[i]) for i in snapshot], firsts_meta))
        self.blocks_dispatched += 1
        if self.on_block is not None:
            self.on_block("dispatch", len(snapshot))
        return True

    def _retire_loop_block(self):
        """Fetch the OLDEST in-flight loop block -- ONE counted host
        copy of its whole result tree (the ``fetch`` hook; the async
        copies have been overlapping newer blocks' compute) -- and
        de-multiplex: folded first tokens, then each slot's ring
        prefix.  The host-side finish test in ``_emit`` is the
        authority; the device's stop detection never stops a row
        EARLIER than it, so truncation here only ever discards
        overshoot."""
        blk = self._loop_inflight.popleft()
        if self.on_block is not None:
            self.on_block("retire", len(blk.snapshot))
        fetched = self._fetch(blk.tree)
        emitted = np.asarray(fetched["emitted"])
        counts = np.asarray(fetched["counts"])
        self.steps += int(fetched["steps"])
        self.blocks_retired += 1
        self.accepted_tokens += int(np.asarray(fetched["accepted"]).sum())
        self.draft_tokens += int(np.asarray(fetched["drafted"]).sum())
        if "firsts" in fetched:
            first_tokens = np.asarray(fetched["firsts"])
            for (slot, request), token in zip(blk.firsts_meta,
                                              first_tokens):
                if self.slots[slot] is request and not request.done:
                    token = int(token)
                    self.current[slot] = token
                    self._emit(request, token)
        for slot, request in blk.snapshot:
            if request is None or self.slots[slot] is not request:
                continue
            for index in range(int(counts[slot])):
                if self.slots[slot] is not request or request.done:
                    break
                token = int(emitted[slot, index])
                self.current[slot] = token
                self._emit(request, token)
        lengths_fetched = np.asarray(fetched["lengths"])
        for slot, request in blk.snapshot:
            if request is not None and self.slots[slot] is request \
                    and not request.done:
                self.lengths[slot] = int(lengths_fetched[slot])
        if not self._loop_inflight:
            self._lengths_upper = self.lengths.copy()

    # -- paged-cache bookkeeping -------------------------------------------

    def _ensure_pages(self, slot: int, upto_tokens: int) -> bool:
        """Cover the slot's logical positions [0, upto_tokens) with
        physical pages.  Under pool pressure: with blocks in flight the
        caller must retire them first (their writes still route through
        the already-dispatched table), otherwise the YOUNGEST other
        occupant is preempted -- its generation resumes later from its
        committed tokens, exactly like :meth:`recover`."""
        if self._pages is None:
            return True
        pages = self._pages.pages_for(
            min(int(upto_tokens), self.max_seq), self.kv_page_tokens)
        if self._pages.ensure(slot, pages):
            return True
        if self._inflight or self._loop_inflight:
            return False
        while True:
            victims = [(occupant.admit_seq, index)
                       for index, occupant in enumerate(self.slots)
                       if occupant is not None and index != slot]
            if not victims:
                return False
            self._evict_slot(max(victims)[1])
            if self._pages.ensure(slot, pages):
                return True

    def _sync_page_table(self) -> None:
        """Fold the allocator's dirty rows into the device page table
        (tiny int32 uploads that ride the next dispatch)."""
        if self._pages is None or not self._pages.dirty:
            return
        table = self.cache["page_table"]
        for slot, row in self._pages.dirty.items():
            table = table.at[slot].set(
                jnp.asarray(row, dtype=jnp.int32))
        self._pages.dirty.clear()
        self.cache["page_table"] = table

    def _evict_slot(self, slot: int) -> None:
        """Preempt one slot for its pages: rebase the request onto its
        committed tokens and put it at the FRONT of the queue, so it
        re-admits (re-prefilling prompt + committed, emitting nothing
        twice) as soon as the pool breathes."""
        request = self.slots[slot]
        if request is None:
            return
        self._rebase(request)
        request.slot = -1
        request.prefill_pos = 0
        self._pending_first.pop(slot, None)
        self._prefilling = [s for s in self._prefilling if s != slot]
        self._free_slot(slot)
        self.pending.insert(0, request)
        self.evictions += 1

    def _rebase(self, request: Request) -> None:
        """Fold the request's committed tokens into its prompt so a
        fresh admission resumes generation where it left off.  The sum
        always fits: ``prompt + committed`` IS the host finish test's
        total, and a request at ``max_seq`` has already finished."""
        request.prompt_tokens = list(request.base_prompt) \
            + [int(token) for token in request.committed]
        request.rebased = len(request.committed)

    def recover(self) -> int:
        """Rebuild device state after a device-level failure (an XLA
        raise mid-block, a chaos ``decode_block`` kill): drop every
        in-flight block and chained carry, reset the cache and page
        pool, and re-queue each live request to resume from its LAST
        EMITTED token -- prompt + committed re-prefill and generation
        continues under the remaining budget; nothing already delivered
        is re-emitted.  Returns how many requests were revived."""
        revived = []
        for slot in range(self.max_slots):
            request, self.slots[slot] = self.slots[slot], None
            if request is None or request.done:
                continue
            self._rebase(request)
            request.slot = -1
            request.prefill_pos = 0
            revived.append(request)
        self.pending = revived + self.pending
        self._prefilling.clear()
        self._pending_first.clear()
        self._inflight.clear()
        self._loop_inflight.clear()
        self._chain = None
        self._loop_chain = None
        self._active_dev = None
        self._temps_dev = None
        self._force_inactive.clear()
        self.lengths[:] = 0
        self._lengths_upper[:] = 0
        self.current[:] = 0
        self.temperatures[:] = 0.0
        self.decoding[:] = False
        if self._pages is not None:
            self._pages.reset()
            self.cache = init_paged_cache(
                self.config, self.max_slots, self.max_seq,
                self.kv_page_tokens, self._pages.total)
        else:
            self.cache = llama.init_cache(self.config, self.max_slots,
                                          self.max_seq)
        if self._cache_put is not None:
            self.cache = self._cache_put(self.cache)
        self.recoveries += 1
        return len(revived)

    def resume_request(self, request: Request, committed) -> bool:
        """Fold an externally journaled committed prefix into a
        just-submitted request (process-level adoption/migration,
        ISSUE 13): the same ``_rebase`` discipline page-pool
        preemption and ``recover()`` use, applied across a process
        boundary -- prompt + committed re-prefill, generation
        continues under the remaining budget, nothing already
        streamed is re-emitted (the caller pre-seeds its collector
        with the committed tokens instead).

        Returns False when the prefix already FINISHED the request
        (its last token is EOS, the budget is spent, or the sequence
        is at max_seq -- the process died between the final emit and
        delivery): the request is withdrawn, not resumed -- decoding
        past a finished prefix would append a spurious tail to text
        the contract promises byte-identical.  The caller completes
        from the committed tokens it already holds."""
        request.committed = [int(token) for token in committed]
        request.generated = len(request.committed)
        if request.generated:
            # ttft/tpot stamps would span the failover, not serving:
            # a resumed request reports no latency stats.
            request.submit_time = 0.0
        self._rebase(request)
        finished = bool(request.committed) and (
            request.committed[-1] in request.eos_tokens
            or request.generated >= request.max_new_tokens
            or len(request.prompt_tokens) >= self.max_seq)
        if finished:
            request.done = True
            if request in self.pending:
                self.pending.remove(request)
        return not finished

    def export_state(self) -> list[dict]:
        """Committed state of every live (not finished) request --
        the drain/migration handoff record.  Each entry is enough for
        :meth:`import_state` on a peer to resume the request at its
        committed prefix."""
        entries = []
        live = [request for request in self.slots
                if request is not None] + list(self.pending)
        for request in live:
            if request.done:
                continue
            entries.append({
                "request_id": request.request_id,
                "prompt": [int(t) for t in request.base_prompt],
                "committed": [int(t) for t in request.committed],
                "max_new_tokens": int(request.max_new_tokens),
                "temperature": float(request.temperature),
                "eos_tokens": [int(t) for t in request.eos_tokens]})
        return entries

    def import_state(self, entries, emit_factory=None) -> int:
        """Resume exported requests at their committed prefix.
        ``emit_factory(entry) -> emit`` wires each request's token
        callback (None = no emission).  Returns how many were
        queued."""
        count = 0
        for entry in entries:
            request = Request(
                request_id=str(entry["request_id"]),
                prompt_tokens=list(entry["prompt"]),
                max_new_tokens=int(entry.get("max_new_tokens", 128)),
                temperature=float(entry.get("temperature", 0.0)),
                eos_tokens=tuple(entry.get("eos_tokens", ())))
            if emit_factory is not None:
                request.emit = emit_factory(entry)
            self.submit(request)
            self.resume_request(request, entry.get("committed", ()))
            count += 1
        return count

    @property
    def prefix_hits(self) -> int:
        """Prompt pages adopted from the shared-prefix index."""
        return self._pages.prefix_hits if self._pages is not None else 0

    @property
    def prefix_lookups(self) -> int:
        """Whole prompt pages the index was consulted for."""
        return self._pages.prefix_lookups \
            if self._pages is not None else 0

    def prefix_hit_rate(self) -> float:
        """Adopted fraction of looked-up prompt pages (0.0 when the
        cache is off or nothing was looked up)."""
        lookups = self.prefix_lookups
        return self.prefix_hits / lookups if lookups else 0.0

    def reset_prefix_stats(self) -> None:
        """Zero the hit/lookup counters (bench warm-phase isolation)."""
        if self._pages is not None:
            self._pages.prefix_hits = 0
            self._pages.prefix_lookups = 0

    def take_request_stats(self) -> list[dict]:
        """Drain per-request latency stamps ({"ttft_ms", "tpot_ms",
        "tokens"}) recorded at finish -- the serving element feeds them
        to the telemetry plane."""
        stats, self._request_stats = self._request_stats, []
        return stats

    def _emit(self, request: Request, token: int):
        request.generated += 1
        self.tokens_emitted += 1
        now = time.perf_counter()
        if request.generated == 1:
            request.first_time = now
        request.committed.append(token)
        # Cache position of the token currently being generated is
        # len(prompt) + generated - 1; the last usable write position is
        # max_seq - 2 (max_seq - 1 is the trash row), so finish once the
        # sequence would need to write past it.  ``rebased`` backs out
        # tokens recover()/eviction folded into the prompt, so a
        # resumed request keeps the original arithmetic.
        total_len = len(request.prompt_tokens) + request.generated \
            - request.rebased
        finished = (token in request.eos_tokens
                    or request.generated >= request.max_new_tokens
                    or total_len >= self.max_seq)
        if request.emit is not None:
            request.emit(request.request_id, token, finished)
        if finished:
            request.done = True
            if request.submit_time:
                ttft_ms = (request.first_time - request.submit_time) \
                    * 1000.0
                tpot_ms = (now - request.first_time) * 1000.0 \
                    / (request.generated - 1) \
                    if request.generated > 1 else 0.0
                self._request_stats.append(
                    {"ttft_ms": round(ttft_ms, 3),
                     "tpot_ms": round(tpot_ms, 3),
                     "tokens": request.generated,
                     "tenant": request.tenant,
                     "cls": request.qos_class})
            self._free_slot(request.slot)

    def _free_slot(self, slot: int):
        """Release a slot's host-side state (finish, cancel and
        eviction share this -- any new per-slot bookkeeping belongs
        here)."""
        self.slots[slot] = None
        self.lengths[slot] = 0
        self._lengths_upper[slot] = 0
        self.current[slot] = 0
        self.temperatures[slot] = 0.0
        self._temps_dev = None
        self.decoding[slot] = False
        self._active_dev = None
        if self.device_loop:
            self._force_inactive.add(slot)
        if self._pages is not None:
            self._pages.release(slot)

    def cancel(self, request_id: str) -> bool:
        """Abandon a request by id: pending requests leave the queue; an
        admitted request frees its slot immediately, so it stops
        occupying a device batch row from the next dispatch on.  Tokens
        for it inside already-in-flight fused blocks are discarded at
        retire via the snapshot identity check -- the same overshoot
        semantics a finished request has.  ``emit`` is never called for
        a cancelled request.  Returns True when a request was found."""
        found = False
        for request in list(self.pending):
            if request.request_id == request_id:
                self.pending.remove(request)
                request.done = True
                found = True
        for slot, request in enumerate(self.slots):
            if request is None or request.request_id != request_id:
                continue
            request.done = True
            self._free_slot(slot)
            # A first-token sample parked for the next block dispatch
            # belongs to this slot's (now cancelled) occupant.
            self._pending_first.pop(slot, None)
            found = True
        return found

    # -- introspection -----------------------------------------------------

    @property
    def active_count(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    @property
    def blocks_in_flight(self) -> int:
        """Dispatched-but-unretired fused/loop decode blocks; drive
        step() until this reaches 0 to drain them."""
        return len(self._inflight) + len(self._loop_inflight)

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        steps = 0
        while (self.pending or self.active_count or self._inflight
               or self._loop_inflight) and steps < max_steps:
            self.step()
            steps += 1
        return steps


# ---------------------------------------------------------------------------
# Cross-stream micro-batching for async pipeline elements.

class MicroBatcher:
    """Cross-stream micro-batching admission for async pipeline elements.

    Generalizes the Detector's parked-frame admission (r5) so ANY async
    element coalesces frames parked at its stage -- from every stream in
    the process -- into one batched device call.  It shares the
    ContinuousBatcher's admission discipline: frames submitted in one
    event-loop burst flush together (``schedule_flush`` defers to the
    engine's mailbox drain, so a lone frame pays no added latency),
    groups form per signature key (stacking float16 with float32 frames
    would silently promote; mixed shapes cannot stack at all), ragged
    groups pad to power-of-two compile buckets (:func:`pad_to_bucket`),
    and all device work runs on a single daemon worker thread -- the
    event loop never blocks on a dispatch, a fetch, or a first-use jit
    compile.

    The element supplies three callables:

    - ``run(context, key, payloads) -> result``: stack + dispatch ONE
      batched device call for a same-key group (worker thread; raising
      errors every frame of that group only);
    - ``finish(context, key, entries, result)``: fetch + complete each
      parked frame from its row (worker thread; ``entries`` is
      ``[(complete, payload), ...]`` in submission order);
    - ``context()``: model snapshot taken at flush time -- a queued
      batch must dispatch against the weights it was built with (or
      fail cleanly if their devices died), never a half-swapped model.

    The worker dispatches EVERY group of a flush before fetching any
    (device work pipelines across groups).  Submit/flush/stop run on
    the event loop; only the queue crosses threads.

    Scope note (found by the r07 bench attempt): a micro-batched
    element on a REPLICATED placed stage is not yet supported -- the
    replica hop lands each parked frame's inputs on ITS replica's
    submesh, and a cross-replica group would stack arrays from
    different device sets into one dispatch (XLA rejects the mix).
    Replicate synchronous stages; async elements already spread load
    through their own cross-stream batching.
    """

    def __init__(self, run: Callable, finish: Callable,
                 context: Callable, schedule_flush: Callable,
                 logger=None, name: str = "microbatch"):
        self._run = run
        self._finish = finish
        self._context = context
        self._schedule_flush = schedule_flush
        self._logger = logger
        self.name = name
        self._pending: list[tuple] = []  # (rank, seq, key, payload, complete)
        self._flush_scheduled = False
        self._queue: queue.Queue | None = None
        # perf counters (tests assert dispatches < frames)
        self.submitted = 0
        self.dispatches = 0
        self.flushes = 0

    def submit(self, key, payload, complete, max_batch: int = 8,
               rank: int = 0):
        """Park one frame's work.  Flushes immediately at ``max_batch``
        pending, otherwise once the engine's mailboxes drain -- every
        frame of the burst joins the same batched dispatch.  ``rank``
        is the frame's QoS class rank (ISSUE 12): a flush dispatches
        best-ranked groups first, so an interactive frame's batch hits
        the device before a batch-class group parked in the same
        burst; all-equal ranks keep submission order exactly."""
        self._ensure_worker()
        self._pending.append((int(rank), self.submitted, key, payload,
                              complete))
        self.submitted += 1
        if len(self._pending) >= int(max_batch):
            self.flush()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            self._schedule_flush(self._flush_deferred)

    def _ensure_worker(self):
        if self._queue is None:
            self._queue = queue.Queue()
            threading.Thread(target=self._worker, args=(self._queue,),
                             daemon=True,
                             name=f"microbatch-{self.name}").start()

    def _flush_deferred(self):
        self._flush_scheduled = False
        self.flush()

    def flush(self):
        """Group pending frames by key (submission order preserved
        within a group) and hand the burst to the worker.  Groups
        dispatch in best-(rank, submission) order -- the QoS plane;
        with all-default ranks that IS first-submission order, the
        pre-QoS behavior."""
        pending, self._pending = self._pending, []
        if not pending:
            return
        if self._queue is None:             # stopped mid-burst
            for _, _, _, _, complete in pending:
                complete_error(complete, f"{self.name} stopped")
            return
        pending.sort(key=lambda entry: entry[:2])
        groups: dict = {}
        for _, _, key, payload, complete in pending:
            groups.setdefault(key, []).append((complete, payload))
        self.flushes += 1
        self.dispatches += len(groups)
        self._queue.put((self._context(), list(groups.items())))

    def stop(self):
        """Flush pending frames, then retire the worker (in-flight
        batches drain first).  A later submit lazily starts a fresh
        worker -- without this the thread would pin the element (and
        its device weights) forever."""
        self.flush()
        work, self._queue = self._queue, None
        if work is not None:
            work.put(None)                  # drain-then-exit sentinel

    # -- worker side -------------------------------------------------------

    def _worker(self, work: "queue.Queue"):
        while True:
            item = work.get()
            if item is None:
                return
            self._run_groups(*item)

    def _run_groups(self, context, groups):
        """Dispatch every group first, then fetch/complete each.  A
        failing dispatch errors every frame of ITS group -- anything
        not completed here would stay parked forever."""
        dispatched = []
        for key, entries in groups:
            try:
                result = self._run(context, key,
                                   [payload for _, payload in entries])
            except Exception as error:
                if self._logger is not None:
                    self._logger.exception(
                        "%s: batched dispatch failed", self.name)
                for complete, _ in entries:
                    complete_error(complete,
                                   f"{self.name} dispatch: {error}")
                continue
            dispatched.append((key, entries, result))
        for key, entries, result in dispatched:
            try:
                self._finish(context, key, entries, result)
            except Exception as error:      # pragma: no cover - defensive
                if self._logger is not None:
                    self._logger.exception(
                        "%s: batch finish failed", self.name)
                for complete, _ in entries:
                    complete_error(complete, str(error))


def complete_error(complete: Callable, diagnostic: str):
    """Error one parked frame (import-cycle-free StreamEvent access)."""
    from ..pipeline.stream import StreamEvent
    complete(StreamEvent.ERROR, {"diagnostic": diagnostic})


class MicroBatchElement:
    """Mixin holding the one copy of the element-side MicroBatcher glue
    (lazy creation against the engine's drain callback, key-failure
    error path, ``max_batch`` resolution on the event loop, stop/teardown)
    shared by the Detector, ImageResize, and AudioFFT.

    Subclasses implement ``batch_key(payload)`` (grouping signature,
    resolved on the event loop; raising errors ONLY that frame),
    ``batch_run(context, key, payloads)`` and
    ``batch_finish(context, key, entries, result)`` (worker thread),
    and optionally ``batch_context()`` (model snapshot at flush time).
    """

    _batcher: MicroBatcher | None = None

    def batch_context(self):
        return None

    def batch_key(self, payload):
        raise NotImplementedError

    def batch_run(self, context, key, payloads):
        raise NotImplementedError

    def batch_finish(self, context, key, entries, result):
        raise NotImplementedError

    def submit_microbatch(self, complete, payload,
                          diagnostic: str = "bad input"):
        if self._batcher is None:
            self._batcher = MicroBatcher(
                run=self.batch_run, finish=self.batch_finish,
                context=self.batch_context,
                schedule_flush=(self.pipeline.runtime.engine
                                .post_when_drained),
                logger=self.logger, name=self.name)
        max_batch, _ = self.get_parameter("max_batch", 8)
        try:
            key = self.batch_key(payload)
        except Exception as error:      # malformed frame: only ITS
            complete_error(complete,     # complete errors
                           f"{diagnostic}: {error}")
            return
        # Unified QoS admission (ISSUE 12): the parked frame's class
        # rank orders the flush, so the batcher honors the same
        # priority vocabulary as the stage credits.  Resolved on the
        # event loop where the current-stream context is intact.
        rank = 0
        qos = getattr(self.pipeline, "qos", None)
        if qos is not None:
            stream = self.pipeline.current_stream()
            if stream is not None:
                rank = qos.class_rank(getattr(stream, "qos_class",
                                              None))
        self._batcher.submit(key, payload, complete,
                             max_batch=int(max_batch), rank=rank)

    def stop_microbatcher(self):
        """Flush + retire (a later submit lazily starts a fresh one)."""
        batcher, self._batcher = self._batcher, None
        if batcher is not None:
            batcher.stop()

    def stop_stream(self, stream, stream_id):
        if self._batcher is not None:
            # The stopping stream's parked frames must not linger in a
            # half-collected burst.  The batcher itself is SHARED
            # across streams: retire the worker only when this was the
            # last live stream (the engine pops the stream before
            # stop_stream fires), so sibling streams keep their warm
            # worker and the cross-stream batching counters.
            self._batcher.flush()
            if not self.pipeline.streams:
                self.stop_microbatcher()
        return super().stop_stream(stream, stream_id)
