"""Continuous batching for the LLM serving element (BASELINE config 3).

The reference's chat element forwards to an external Ollama server
(reference examples/llm/elements.py:92-212); here serving is native: a
slot-based continuous batcher owns a batched KV cache in HBM and a decode
loop on-device.

Design (the "hard part" flagged in SURVEY.md section 7): many actor
requests merge into device batches and de-multiplex back to per-request
token streams.

- ``max_slots`` sequences decode together as one [B] ``decode_step``;
- new requests are prefix-filled with a batch-1 ``prefill`` into a scratch
  cache, then scattered into their slot of the batched cache (jitted,
  donated -- no host round-trip);
- finished sequences (EOS or token budget) free their slot immediately;
  admission happens between decode steps, so a long generation never
  blocks a short one (continuous, not static, batching);
- the engine is synchronous and thread-agnostic: ``step()`` advances one
  decode tick and returns emitted (request_id, token) pairs.  The serving
  element runs it on a worker thread and pushes tokens to actor queues.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import llama

__all__ = ["Request", "ContinuousBatcher"]


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_tokens: list[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    eos_tokens: tuple = ()
    emit: Callable | None = None     # fn(request_id, token_id, finished)
    # runtime state
    slot: int = -1
    generated: int = 0
    done: bool = False


@partial(jax.jit, donate_argnames=("big", ))
def _scatter_cache(big: dict, small: dict, slot: jax.Array) -> dict:
    """Copy a batch-1 prefill cache into slot ``slot`` of the batched
    cache.  Copies the whole max_seq extent (prefill wrote only the
    prompt's positions; the rest is zeros which decode masks out anyway
    -- a static-shape copy XLA handles in one fused kernel)."""
    k = jax.lax.dynamic_update_slice_in_dim(
        big["k"], small["k"], slot, axis=1)
    v = jax.lax.dynamic_update_slice_in_dim(
        big["v"], small["v"], slot, axis=1)
    return {"k": k, "v": v}


@jax.jit
def _select_tokens(key: jax.Array, logits: jax.Array,
                   temperatures: jax.Array) -> jax.Array:
    """Per-slot sampling in one draw: rows with temperature 0 take the
    argmax, rows with temperature > 0 take a categorical sample at their
    OWN temperature (scale each row's logits before one batched draw)."""
    greedy = jnp.argmax(logits, axis=-1)
    safe = jnp.maximum(temperatures, 0.05)[:, None]
    sampled = jax.random.categorical(
        key, logits.astype(jnp.float32) / safe, axis=-1)
    return jnp.where(temperatures > 0, sampled, greedy)


class ContinuousBatcher:
    def __init__(self, params, config: llama.LlamaConfig,
                 max_slots: int = 8, max_seq: int | None = None,
                 prefill_chunk: int = 512, rng_seed: int = 0):
        self.params = params
        self.config = config
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq
        self.prefill_chunk = prefill_chunk
        self.cache = llama.init_cache(config, max_slots, self.max_seq)
        self.lengths = np.zeros(max_slots, dtype=np.int32)
        self.current = np.zeros(max_slots, dtype=np.int32)
        self.temperatures = np.zeros(max_slots, dtype=np.float32)
        self.slots: list[Request | None] = [None] * max_slots
        self.pending: list[Request] = []
        self._key = jax.random.PRNGKey(rng_seed)
        # perf counters
        self.tokens_emitted = 0
        self.steps = 0
        self.prefill_tokens = 0

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request):
        if len(request.prompt_tokens) >= self.max_seq:
            request.prompt_tokens = \
                request.prompt_tokens[-(self.max_seq // 2):]
        self.pending.append(request)

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self.slots) if r is None]

    def _admit(self):
        free = self._free_slots()
        while free and self.pending:
            slot = free.pop(0)
            request = self.pending.pop(0)
            self._prefill_into_slot(slot, request)

    def _prefill_into_slot(self, slot: int, request: Request):
        # An empty prompt still needs one position of context to sample
        # from; condition it on a single pad token rather than indexing
        # into uninitialised padding.
        if not request.prompt_tokens:
            request.prompt_tokens = [0]
        prompt = np.asarray(request.prompt_tokens, dtype=np.int32)
        length = len(prompt)
        # pad to the chunk grid to bound recompilation
        padded = int(np.ceil(length / self.prefill_chunk)
                     * self.prefill_chunk)
        padded = min(padded, self.max_seq)
        tokens = np.zeros((1, padded), dtype=np.int32)
        tokens[0, :length] = prompt
        scratch = llama.init_cache(self.config, 1, self.max_seq)
        logits, scratch = llama.prefill(
            self.params, self.config, jnp.asarray(tokens), scratch,
            jnp.zeros((1,), dtype=jnp.int32))
        self.cache = _scatter_cache(self.cache, scratch, jnp.int32(slot))
        first = self._sample(logits[:, length - 1, :],
                             request.temperature)
        first_token = int(jax.device_get(first)[0])
        self.prefill_tokens += length
        request.slot = slot
        self.slots[slot] = request
        self.lengths[slot] = length
        self.current[slot] = first_token
        self.temperatures[slot] = request.temperature
        self._emit(request, first_token)

    # -- decode ------------------------------------------------------------

    def _sample(self, logits, temperature: float):
        if temperature and temperature > 0:
            self._key, sub = jax.random.split(self._key)
            return llama.temperature_sample(sub, logits, temperature)
        return llama.greedy_sample(logits)

    def step(self) -> int:
        """Admit pending requests, run one decode tick across all active
        slots, emit tokens.  Returns number of active slots."""
        self._admit()
        active = [i for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return 0
        tokens = jnp.asarray(self.current)
        lengths = jnp.asarray(self.lengths)
        logits, self.cache = llama.decode_step(
            self.params, self.config, tokens, self.cache, lengths)
        self._key, sub = jax.random.split(self._key)
        next_tokens = np.asarray(jax.device_get(_select_tokens(
            sub, logits, jnp.asarray(self.temperatures))), dtype=np.int32)
        self.steps += 1
        for i in active:
            request = self.slots[i]
            self.lengths[i] += 1
            token = int(next_tokens[i])
            self.current[i] = token
            self._emit(request, token)
        return len(active)

    def _emit(self, request: Request, token: int):
        request.generated += 1
        self.tokens_emitted += 1
        finished = (token in request.eos_tokens
                    or request.generated >= request.max_new_tokens
                    or self.lengths[request.slot] >= self.max_seq - 1)
        if request.emit is not None:
            request.emit(request.request_id, token, finished)
        if finished:
            request.done = True
            self.slots[request.slot] = None
            self.lengths[request.slot] = 0
            self.current[request.slot] = 0
            self.temperatures[request.slot] = 0.0

    # -- introspection -----------------------------------------------------

    @property
    def active_count(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        steps = 0
        while (self.pending or self.active_count) and steps < max_steps:
            self.step()
            steps += 1
        return steps
