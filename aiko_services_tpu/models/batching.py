"""Continuous batching for the LLM serving element (BASELINE config 3).

The reference's chat element forwards to an external Ollama server
(reference examples/llm/elements.py:92-212); here serving is native: a
slot-based continuous batcher owns a batched KV cache in HBM and a decode
loop on-device.

Design (the "hard part" flagged in SURVEY.md section 7): many actor
requests merge into device batches and de-multiplex back to per-request
token streams.

- ``max_slots`` sequences decode together as one [B] ``decode_step``;
- admission is CHUNKED and INTERLEAVED: each ``step()`` prefills at most
  ``prefill_chunk`` prompt tokens -- written straight into the admitted
  slot's region of the batched cache (``llama.prefill_into_slot``; no
  scratch cache, no full-extent scatter) -- and then runs one decode
  tick for every already-generating slot.  A long prompt therefore
  never stalls active decodes beyond one chunk's latency, and admission
  costs one in-place chunk write instead of a max_seq-extent copy;
- finished sequences (EOS or token budget) free their slot immediately;
  a long generation never blocks a short one (continuous, not static,
  batching);
- the engine is synchronous and thread-agnostic: ``step()`` advances one
  tick and invokes per-request ``emit`` callbacks.  The serving element
  runs it on the event engine and pushes tokens to actor queues.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import llama

__all__ = ["Request", "ContinuousBatcher"]


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_tokens: list[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    eos_tokens: tuple = ()
    emit: Callable | None = None     # fn(request_id, token_id, finished)
    # runtime state
    slot: int = -1
    prefill_pos: int = 0             # prompt tokens already written
    generated: int = 0
    done: bool = False


_select_tokens = jax.jit(llama.select_tokens)


class ContinuousBatcher:
    def __init__(self, params, config: llama.LlamaConfig,
                 max_slots: int = 8, max_seq: int | None = None,
                 prefill_chunk: int = 512, rng_seed: int = 0,
                 decode_block: int = 1):
        self.params = params
        self.config = config
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq
        self.prefill_chunk = min(prefill_chunk, self.max_seq)
        # >1: fuse that many decode iterations (sampling included) into
        # one device dispatch when no admission is in flight -- the host
        # round trip stops bounding tokens/s.  Tokens a request emits
        # past its EOS/budget inside a block are discarded host-side.
        self.decode_block = max(1, int(decode_block))
        self.cache = llama.init_cache(config, max_slots, self.max_seq)
        self.lengths = np.zeros(max_slots, dtype=np.int32)
        self.current = np.zeros(max_slots, dtype=np.int32)
        self.temperatures = np.zeros(max_slots, dtype=np.float32)
        self.decoding = np.zeros(max_slots, dtype=bool)
        self.slots: list[Request | None] = [None] * max_slots
        self.pending: list[Request] = []
        self._prefilling: list[int] = []      # slot FIFO, round-robin
        self._key = jax.random.PRNGKey(rng_seed)
        # perf counters
        self.tokens_emitted = 0
        self.steps = 0
        self.prefill_tokens = 0

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request):
        if len(request.prompt_tokens) >= self.max_seq:
            request.prompt_tokens = \
                request.prompt_tokens[-(self.max_seq // 2):]
        # An empty prompt still needs one position of context to sample
        # from; condition it on a single pad token rather than indexing
        # into uninitialised padding.
        if not request.prompt_tokens:
            request.prompt_tokens = [0]
        self.pending.append(request)

    def _admit(self):
        """Assign free slots to pending requests (no device work: the
        prompt is written chunk-at-a-time by ``_prefill_tick``)."""
        for slot, occupant in enumerate(self.slots):
            if occupant is not None or not self.pending:
                continue
            request = self.pending.pop(0)
            request.slot = slot
            request.prefill_pos = 0
            self.slots[slot] = request
            self.lengths[slot] = 0
            self.current[slot] = 0
            self.temperatures[slot] = request.temperature
            self.decoding[slot] = False
            self._prefilling.append(slot)

    def _prefill_tick(self):
        """Write at most ONE chunk (<= prefill_chunk tokens) of the
        longest-waiting admitting prompt into its slot's cache region.
        Bounds the latency a decode tick can suffer from admissions."""
        if not self._prefilling:
            return
        slot = self._prefilling.pop(0)
        request = self.slots[slot]
        if request is None:                     # cancelled while waiting
            return
        prompt = request.prompt_tokens
        # Clamp the write start so a full chunk always fits inside the
        # cache (a spilling dynamic_update_slice would clamp internally
        # and corrupt earlier positions).  A clamped start re-writes the
        # overlap with byte-identical KV (same tokens, same positions),
        # so correctness is unaffected and only the final chunk pays.
        start = min(request.prefill_pos, self.max_seq - self.prefill_chunk)
        chunk_tokens = prompt[start:start + self.prefill_chunk]
        # Always pad to the full chunk: one compiled shape for every
        # admission.  Pad positions hold garbage KV, but decode writes
        # each position before the length mask ever admits it, and the
        # causal prefill mask never looks past the query position.
        padded = np.zeros((1, self.prefill_chunk), dtype=np.int32)
        padded[0, :len(chunk_tokens)] = chunk_tokens
        logits, self.cache = llama.prefill_into_slot(
            self.params, self.config, jnp.asarray(padded), self.cache,
            jnp.int32(slot), jnp.int32(start))
        self.prefill_tokens += start + len(chunk_tokens) \
            - request.prefill_pos
        request.prefill_pos = start + len(chunk_tokens)
        if request.prefill_pos < len(prompt):
            self._prefilling.append(slot)       # more chunks to go
            return
        # Final chunk: sample the first generated token from the last
        # real prompt position's logits and hand the slot to decode.
        last = len(prompt) - start - 1
        first = self._sample(logits[:, last, :], request.temperature)
        first_token = int(jax.device_get(first)[0])
        self.lengths[slot] = len(prompt)
        self.current[slot] = first_token
        self.decoding[slot] = True
        self._emit(request, first_token)

    # -- decode ------------------------------------------------------------

    def _sample(self, logits, temperature: float):
        if temperature and temperature > 0:
            self._key, sub = jax.random.split(self._key)
            return llama.temperature_sample(sub, logits, temperature)
        return llama.greedy_sample(logits)

    def step(self) -> int:
        """Admit pending requests, advance at most one prefill chunk,
        run one decode tick across all generating slots, emit tokens.
        Returns the number of occupied slots (prefilling + decoding)."""
        self._admit()
        self._prefill_tick()
        decoding = [i for i in range(self.max_slots) if self.decoding[i]]
        if decoding:
            if self.decode_block > 1 and not self._prefilling:
                self._decode_block_tick(decoding)
            else:
                # Admissions in flight: single ticks keep the
                # chunked-prefill interleaving guarantee.
                self._decode_tick(decoding)
        return sum(1 for r in self.slots if r is not None)

    def _decode_tick(self, decoding: list[int]):
        tokens = jnp.asarray(self.current)
        # Rows not decoding (empty or mid-prefill) still flow through the
        # batched step; route their KV write to the trash position
        # max_seq-1, which real content never occupies (decode finishes
        # at lengths >= max_seq-1, so its last write is max_seq-2, and
        # the masks never admit max_seq-1 for a live row).
        write_positions = np.where(self.decoding, self.lengths,
                                   self.max_seq - 1).astype(np.int32)
        logits, self.cache = llama.decode_step(
            self.params, self.config, tokens, self.cache,
            jnp.asarray(write_positions))
        self._key, sub = jax.random.split(self._key)
        next_tokens = np.asarray(jax.device_get(_select_tokens(
            sub, logits, jnp.asarray(self.temperatures))), dtype=np.int32)
        self.steps += 1
        for i in decoding:
            request = self.slots[i]
            self.lengths[i] += 1
            token = int(next_tokens[i])
            self.current[i] = token
            self._emit(request, token)

    def _decode_block_tick(self, decoding: list[int]):
        """decode_block fused iterations in one dispatch
        (llama.decode_block); de-multiplex host-side, truncating each
        request at its EOS/budget (overshoot KV lands beyond the freed
        slot's next occupant's length mask, so it is never read)."""
        self._key, sub = jax.random.split(self._key)
        emitted, self.cache = llama.decode_block(
            self.params, self.config, jnp.asarray(self.current),
            self.cache, jnp.asarray(self.lengths),
            jnp.asarray(self.decoding), jnp.asarray(self.temperatures),
            sub, num_steps=self.decode_block)
        emitted = np.asarray(jax.device_get(emitted))   # [K, B]
        self.steps += 1
        for i in decoding:
            request = self.slots[i]
            for block_step in range(self.decode_block):
                if self.slots[i] is not request:        # finished
                    break
                self.lengths[i] += 1
                token = int(emitted[block_step, i])
                self.current[i] = token
                self._emit(request, token)

    def _emit(self, request: Request, token: int):
        request.generated += 1
        self.tokens_emitted += 1
        finished = (token in request.eos_tokens
                    or request.generated >= request.max_new_tokens
                    or self.lengths[request.slot] >= self.max_seq - 1)
        if request.emit is not None:
            request.emit(request.request_id, token, finished)
        if finished:
            request.done = True
            slot = request.slot
            self.slots[slot] = None
            self.lengths[slot] = 0
            self.current[slot] = 0
            self.temperatures[slot] = 0.0
            self.decoding[slot] = False

    # -- introspection -----------------------------------------------------

    @property
    def active_count(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        steps = 0
        while (self.pending or self.active_count) and steps < max_steps:
            self.step()
            steps += 1
        return steps
