"""Continuous batching for the LLM serving element (BASELINE config 3).

The reference's chat element forwards to an external Ollama server
(reference examples/llm/elements.py:92-212); here serving is native: a
slot-based continuous batcher owns a batched KV cache in HBM and a decode
loop on-device.

Design (the "hard part" flagged in SURVEY.md section 7): many actor
requests merge into device batches and de-multiplex back to per-request
token streams.

- ``max_slots`` sequences decode together as one [B] ``decode_step``;
- admission is CHUNKED and INTERLEAVED: prompt tokens are written
  chunk-at-a-time straight into the admitted slot's region of the
  batched cache (``llama.prefill_into_slot``; no scratch cache, no
  full-extent scatter), interleaved with decode ticks.  With
  ``decode_block == 1`` each ``step()`` prefills at most ONE
  ``prefill_chunk`` -- a long prompt never stalls active decodes beyond
  one chunk's latency.  With ``decode_block > 1`` (the pipelined path,
  below) a burst of admissions prefills one chunk PER admitting slot
  per step: the chunks are async dispatches chained on the cache, so a
  burst costs device time, not host round trips, and decode stall is
  bounded by one fused block's latency anyway;
- finished sequences (EOS or token budget) free their slot immediately;
  a long generation never blocks a short one (continuous, not static,
  batching);
- with ``decode_block > 1`` the decode loop is PIPELINED: the batcher
  keeps ``inflight`` fused blocks in flight, chaining each dispatch off
  the previous block's DEVICE-side carries (tokens/lengths/key/cache --
  ``llama.decode_block`` returns them) so the host never waits a tunnel
  round trip between dispatches; emitted tokens are copied back
  asynchronously and retired one block behind.  A request's tokens past
  its EOS/budget inside in-flight blocks are discarded host-side (the
  same overshoot semantics a single fused block already had);
- the engine is synchronous and thread-agnostic: ``step()`` advances one
  tick and invokes per-request ``emit`` callbacks.  The serving element
  runs it on the event engine and pushes tokens to actor queues.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import llama
from ..utils.misc import next_power_of_two

__all__ = ["Request", "ContinuousBatcher"]

# Batched admission advances at most this many slots per tick: compile
# buckets stay {1, 2, 4, 8} regardless of max_slots (an [8*chunk, dim]
# prefill matmul already feeds the MXU; wider bursts would only add
# power-of-two compile shapes, each a fresh jit of the full model).
_ADMISSION_BURST_MAX = 8


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_tokens: list[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    eos_tokens: tuple = ()
    emit: Callable | None = None     # fn(request_id, token_id, finished)
    # runtime state
    slot: int = -1
    prefill_pos: int = 0             # prompt tokens already written
    generated: int = 0
    done: bool = False


_select_tokens = jax.jit(llama.select_tokens)


class _InflightBlock:
    """One dispatched-but-unretired fused decode block."""
    __slots__ = ("emitted", "snapshot", "firsts", "steps")

    def __init__(self, emitted, snapshot, firsts, steps):
        self.emitted = emitted        # [steps, B] device, copy in flight
        self.snapshot = snapshot      # [(slot, request)] active at dispatch
        # ([(slot, request)], stacked first-token device array) or None:
        # admissions folded into this block, fetched in ONE host copy.
        self.firsts = firsts
        self.steps = steps


class ContinuousBatcher:
    def __init__(self, params, config: llama.LlamaConfig,
                 max_slots: int = 8, max_seq: int | None = None,
                 prefill_chunk: int = 512, rng_seed: int = 0,
                 decode_block: int = 1, inflight: int = 2,
                 cache_put: Callable | None = None):
        self.params = params
        self.config = config
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq
        self.prefill_chunk = min(prefill_chunk, self.max_seq)
        # >1: fuse that many decode iterations (sampling included) into
        # one device dispatch -- the host round trip stops bounding
        # tokens/s.  Tokens a request emits past its EOS/budget inside a
        # block are discarded host-side.
        self.decode_block = max(1, int(decode_block))
        # How many fused blocks to keep in flight (decode_block > 1
        # only).  Each dispatch chains off the previous block's device
        # carries, so depth d hides up to d * block_compute of host
        # round-trip latency behind device work.
        self.inflight = max(1, int(inflight))
        self.cache = llama.init_cache(config, max_slots, self.max_seq)
        # Multichip serving: ``cache_put`` places the initial KV cache
        # onto the serving mesh (e.g. ``lambda c: plan.put(c,
        # llama.cache_specs(config))`` for TP-sharded kv heads) --
        # donation keeps that sharding across every subsequent dispatch,
        # so one placement at init is enough.  Params are pre-sharded by
        # the caller the same way (quantized trees via
        # quant.quantize_specs).
        if cache_put is not None:
            self.cache = cache_put(self.cache)
        self.lengths = np.zeros(max_slots, dtype=np.int32)
        self.current = np.zeros(max_slots, dtype=np.int32)
        self.temperatures = np.zeros(max_slots, dtype=np.float32)
        self.decoding = np.zeros(max_slots, dtype=bool)
        self.slots: list[Request | None] = [None] * max_slots
        self.pending: list[Request] = []
        self._prefilling: list[int] = []      # slot FIFO, round-robin
        self._key = jax.random.PRNGKey(rng_seed)
        # pipelining state (decode_block > 1): device-side carries of
        # the latest dispatched block, cached device mirrors of the
        # active/temperature rows (re-uploaded only when they change),
        # first-token futures from prefill completions not yet folded
        # into a dispatch, and the in-flight block queue.
        self._chain: tuple | None = None      # (tokens_dev, lengths_dev)
        self._active_dev = None
        self._temps_dev = None
        self._pending_first: dict[int, tuple] = {}   # slot -> (req, dev)
        self._inflight: deque[_InflightBlock] = deque()
        # perf counters
        self.tokens_emitted = 0
        self.steps = 0
        self.prefill_tokens = 0

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request):
        if len(request.prompt_tokens) >= self.max_seq:
            request.prompt_tokens = \
                request.prompt_tokens[-(self.max_seq // 2):]
        # An empty prompt still needs one position of context to sample
        # from; condition it on a single pad token rather than indexing
        # into uninitialised padding.
        if not request.prompt_tokens:
            request.prompt_tokens = [0]
        self.pending.append(request)

    def _admit(self):
        """Assign free slots to pending requests (no device work: the
        prompt is written chunk-at-a-time by ``_prefill_tick``)."""
        for slot, occupant in enumerate(self.slots):
            if occupant is not None or not self.pending:
                continue
            request = self.pending.pop(0)
            request.slot = slot
            request.prefill_pos = 0
            self.slots[slot] = request
            self.lengths[slot] = 0
            self.current[slot] = 0
            self.temperatures[slot] = request.temperature
            self._temps_dev = None
            self.decoding[slot] = False
            self._prefilling.append(slot)

    def _prefill_tick(self):
        """Advance admissions by one chunk (<= prefill_chunk tokens)
        each.  Pipelined path (decode_block > 1): every admitting slot
        advances -- a multi-slot burst runs as ONE batched dispatch
        (``llama.prefill_into_slots``: the [N*S, dim] matmuls feed the
        MXU far better than N serialized [S, dim] dispatches), falling
        back to per-slot dispatches for the flash-attention config.
        Synchronous path (decode_block == 1): at most ONE chunk total,
        preserving the one-chunk decode-stall bound (each chunk's
        completion fetch blocks the host there)."""
        if (self.decode_block > 1 and len(self._prefilling) > 1
                and self.config.attention != "flash"):
            self._prefill_tick_batched()
            return
        budget = len(self._prefilling) if self.decode_block > 1 \
            else min(1, len(self._prefilling))
        for _ in range(budget):
            slot = self._prefilling.pop(0)
            request = self.slots[slot]
            if request is None:                 # cancelled while waiting
                continue
            start, chunk_tokens = self._admission_chunk(request)
            padded = np.zeros((1, self.prefill_chunk), dtype=np.int32)
            padded[0, :len(chunk_tokens)] = chunk_tokens
            logits, self.cache = llama.prefill_into_slot(
                self.params, self.config, jnp.asarray(padded),
                self.cache, jnp.int32(slot), jnp.int32(start))
            self._admission_advance(slot, request, start,
                                    len(chunk_tokens), logits)

    def _prefill_tick_batched(self):
        """One chunk for EVERY admitting slot in a single batched
        dispatch.  N is padded up to a power-of-two compile bucket by
        duplicating the first row (idempotent: same slot, same start,
        same tokens -- see llama.prefill_into_slots)."""
        admitting = []
        for _ in range(len(self._prefilling)):
            slot = self._prefilling.pop(0)
            if self.slots[slot] is not None:    # else: cancelled
                admitting.append(slot)
        # Overflow waits one tick (FIFO rotation keeps chunk fairness);
        # see _ADMISSION_BURST_MAX for why the burst is capped.
        self._prefilling.extend(admitting[_ADMISSION_BURST_MAX:])
        admitting = admitting[:_ADMISSION_BURST_MAX]
        if not admitting:
            return
        n = len(admitting)
        bucket = next_power_of_two(n)
        rows = admitting + [admitting[0]] * (bucket - n)
        tokens = np.zeros((bucket, self.prefill_chunk), dtype=np.int32)
        slot_rows = np.zeros(bucket, dtype=np.int32)
        starts = np.zeros(bucket, dtype=np.int32)
        metas = []
        for i, slot in enumerate(rows):
            request = self.slots[slot]
            start, chunk_tokens = self._admission_chunk(request)
            tokens[i, :len(chunk_tokens)] = chunk_tokens
            slot_rows[i] = slot
            starts[i] = start
            metas.append((slot, request, start, len(chunk_tokens)))
        logits, self.cache = llama.prefill_into_slots(
            self.params, self.config, jnp.asarray(tokens), self.cache,
            jnp.asarray(slot_rows), jnp.asarray(starts))
        for i, (slot, request, start, chunk_len) in enumerate(metas[:n]):
            self._admission_advance(slot, request, start, chunk_len,
                                    logits[i:i + 1])

    def _admission_chunk(self, request: Request):
        """(start, chunk tokens) of the request's next prefill chunk.
        The write start clamps so a full chunk always fits inside the
        cache (a spilling dynamic_update_slice would clamp internally
        and corrupt earlier positions); a clamped start re-writes the
        overlap with byte-identical KV (same tokens, same positions), so
        correctness is unaffected and only the final chunk pays.  The
        chunk is always PADDED to prefill_chunk by the caller: one
        compiled shape per admission; pad positions hold garbage KV, but
        decode writes each position before the length mask ever admits
        it, and the causal prefill mask never looks past the query
        position."""
        start = min(request.prefill_pos,
                    self.max_seq - self.prefill_chunk)
        return start, request.prompt_tokens[
            start:start + self.prefill_chunk]

    def _admission_advance(self, slot: int, request: Request,
                           start: int, chunk_len: int, logits):
        """Account one written chunk; on the FINAL chunk, sample the
        first generated token from the last real prompt position's
        logits ([1, S, vocab] row) and hand the slot to decode --
        without fetching on the pipelined path (the device scalar folds
        into the next block dispatch and emits when that block
        retires)."""
        prompt = request.prompt_tokens
        self.prefill_tokens += start + chunk_len - request.prefill_pos
        request.prefill_pos = start + chunk_len
        if request.prefill_pos < len(prompt):
            self._prefilling.append(slot)       # more chunks to go
            return
        last = len(prompt) - start - 1
        first = self._sample(logits[:, last, :], request.temperature)
        self.lengths[slot] = len(prompt)
        self.decoding[slot] = True
        self._active_dev = None
        if self.decode_block > 1:
            # No host copy here: the retire fetches the CONCATENATED
            # firsts array of the block this admission folds into.
            self._pending_first[slot] = (request, first)
        else:
            first_token = int(jax.device_get(first)[0])
            self.current[slot] = first_token
            self._emit(request, first_token)

    # -- decode ------------------------------------------------------------

    def _sample(self, logits, temperature: float):
        if temperature and temperature > 0:
            self._key, sub = jax.random.split(self._key)
            return llama.temperature_sample(sub, logits, temperature)
        return llama.greedy_sample(logits)

    def step(self) -> int:
        """Admit pending requests, advance one prefill chunk per
        admitting slot, dispatch/retire decode work across all
        generating slots, emit tokens.  Returns the number of occupied
        slots (prefilling + decoding)."""
        self._admit()
        self._prefill_tick()
        decoding = [i for i in range(self.max_slots) if self.decoding[i]]
        if self.decode_block > 1:
            if decoding:
                # Top the pipeline up to `inflight` blocks, then retire
                # the oldest: steady state is one dispatch + one retire
                # per step, with the retire's host copy overlapping the
                # newer blocks' device compute.  Stop early once the
                # outstanding blocks already cover every active
                # request's remaining budget (EOS can still cut a
                # stream shorter; that overshoot is discarded).
                remaining = max(
                    self.slots[i].max_new_tokens - self.slots[i].generated
                    for i in decoding if self.slots[i] is not None)
                while (len(self._inflight) < self.inflight
                       and len(self._inflight) * self.decode_block
                       < remaining):
                    self._dispatch_block(decoding)
            if self._inflight:
                self._retire_block()
        elif decoding:
            self._decode_tick(decoding)
        return sum(1 for r in self.slots if r is not None)

    def _decode_tick(self, decoding: list[int]):
        tokens = jnp.asarray(self.current)
        # Rows not decoding (empty or mid-prefill) still flow through the
        # batched step; route their KV write to the trash position
        # max_seq-1, which real content never occupies (decode finishes
        # at lengths >= max_seq-1, so its last write is max_seq-2, and
        # the masks never admit max_seq-1 for a live row).
        write_positions = np.where(self.decoding, self.lengths,
                                   self.max_seq - 1).astype(np.int32)
        logits, self.cache = llama.decode_step(
            self.params, self.config, tokens, self.cache,
            jnp.asarray(write_positions))
        self._key, sub = jax.random.split(self._key)
        next_tokens = np.asarray(jax.device_get(_select_tokens(
            sub, logits, jnp.asarray(self.temperatures))), dtype=np.int32)
        self.steps += 1
        for i in decoding:
            request = self.slots[i]
            self.lengths[i] += 1
            token = int(next_tokens[i])
            self.current[i] = token
            self._emit(request, token)

    def _dispatch_block(self, decoding: list[int]):
        """Enqueue one fused decode block chained off the previous
        block's device carries.  No host synchronization: tokens and
        lengths come from the chain (with prefill-completion overrides
        applied on device), the key chains through the kernel, and the
        emitted tokens start copying to the host asynchronously."""
        if self._chain is None:
            tokens = jnp.asarray(self.current)
            lengths = jnp.asarray(self.lengths)
        else:
            tokens, lengths = self._chain
        first_meta, first_vals = [], []
        for slot in sorted(self._pending_first):
            request, first = self._pending_first[slot]
            tokens = tokens.at[slot].set(first[0])
            lengths = lengths.at[slot].set(len(request.prompt_tokens))
            first_meta.append((slot, request))
            first_vals.append(first)
        self._pending_first.clear()
        if first_vals:
            # ONE device array for all admissions folded into this
            # block: the retire then pays a single host fetch instead of
            # one round trip per admitted request (8 sequential tiny
            # fetches cost ~8 RTTs through the tunnel).
            firsts_dev = jnp.concatenate(first_vals)
            firsts_dev.copy_to_host_async()
            firsts = (first_meta, firsts_dev)
        else:
            firsts = None
        if self._active_dev is None:
            self._active_dev = jnp.asarray(self.decoding)
        if self._temps_dev is None:
            self._temps_dev = jnp.asarray(self.temperatures)
        emitted, tokens_n, lengths_n, self._key, self.cache = \
            llama.decode_block(
                self.params, self.config, tokens, self.cache, lengths,
                self._active_dev, self._temps_dev, self._key,
                num_steps=self.decode_block)
        emitted.copy_to_host_async()
        self._chain = (tokens_n, lengths_n)
        for i in decoding:                      # host mirror (clamped)
            self.lengths[i] = min(self.lengths[i] + self.decode_block,
                                  self.max_seq - 1)
        self._inflight.append(_InflightBlock(
            emitted, [(i, self.slots[i]) for i in decoding], firsts,
            self.decode_block))

    def _retire_block(self):
        """Fetch the OLDEST in-flight block's tokens (the async copy
        has been overlapping newer blocks' compute) and de-multiplex
        host-side, truncating each request at its EOS/budget (overshoot
        KV lands beyond the freed slot's next occupant's length mask,
        so it is never read).  A slot freed and re-admitted while this
        block was in flight is skipped via the request snapshot."""
        blk = self._inflight.popleft()
        emitted = np.asarray(blk.emitted)       # [steps, B]
        self.steps += 1
        if blk.firsts is not None:
            first_meta, firsts_dev = blk.firsts
            first_tokens = np.asarray(firsts_dev)    # one fetch for all
            for (slot, request), token in zip(first_meta, first_tokens):
                if self.slots[slot] is request and not request.done:
                    token = int(token)
                    self.current[slot] = token
                    self._emit(request, token)
        for slot, request in blk.snapshot:
            if request is None or self.slots[slot] is not request:
                continue
            for block_step in range(blk.steps):
                if self.slots[slot] is not request:     # finished
                    break
                token = int(emitted[block_step, slot])
                self.current[slot] = token
                self._emit(request, token)

    def _emit(self, request: Request, token: int):
        request.generated += 1
        self.tokens_emitted += 1
        # Cache position of the token currently being generated is
        # len(prompt) + generated - 1; the last usable write position is
        # max_seq - 2 (max_seq - 1 is the trash row), so finish once the
        # sequence would need to write past it.
        total_len = len(request.prompt_tokens) + request.generated
        finished = (token in request.eos_tokens
                    or request.generated >= request.max_new_tokens
                    or total_len >= self.max_seq)
        if request.emit is not None:
            request.emit(request.request_id, token, finished)
        if finished:
            request.done = True
            self._free_slot(request.slot)

    def _free_slot(self, slot: int):
        """Release a slot's host-side state (finish and cancel paths
        share this -- any new per-slot bookkeeping belongs here)."""
        self.slots[slot] = None
        self.lengths[slot] = 0
        self.current[slot] = 0
        self.temperatures[slot] = 0.0
        self._temps_dev = None
        self.decoding[slot] = False
        self._active_dev = None

    def cancel(self, request_id: str) -> bool:
        """Abandon a request by id: pending requests leave the queue; an
        admitted request frees its slot immediately, so it stops
        occupying a device batch row from the next dispatch on.  Tokens
        for it inside already-in-flight fused blocks are discarded at
        retire via the snapshot identity check -- the same overshoot
        semantics a finished request has.  ``emit`` is never called for
        a cancelled request.  Returns True when a request was found."""
        found = False
        for request in list(self.pending):
            if request.request_id == request_id:
                self.pending.remove(request)
                request.done = True
                found = True
        for slot, request in enumerate(self.slots):
            if request is None or request.request_id != request_id:
                continue
            request.done = True
            self._free_slot(slot)
            # A first-token sample parked for the next block dispatch
            # belongs to this slot's (now cancelled) occupant.
            self._pending_first.pop(slot, None)
            found = True
        return found

    # -- introspection -----------------------------------------------------

    @property
    def active_count(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    @property
    def blocks_in_flight(self) -> int:
        """Dispatched-but-unretired fused decode blocks (pipelined
        path); drive step() until this reaches 0 to drain them."""
        return len(self._inflight)

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        steps = 0
        while (self.pending or self.active_count or self._inflight) \
                and steps < max_steps:
            self.step()
            steps += 1
        return steps
