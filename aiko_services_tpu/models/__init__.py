from . import convert
from . import detector
from . import llama
from . import long_context
from .batching import ContinuousBatcher, Request
from .checkpoint import (Checkpointer, save_pytree, restore_pytree,
                         maybe_restore)
from .tokenizer import ByteTokenizer, load_tokenizer
