from . import llama
from .batching import ContinuousBatcher, Request
from .tokenizer import ByteTokenizer, load_tokenizer
