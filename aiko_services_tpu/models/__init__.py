from . import llama
from . import long_context
from .batching import ContinuousBatcher, Request
from .tokenizer import ByteTokenizer, load_tokenizer
