"""Paged KV cache for continuous-batching LLM serving (ISSUE 8).

The monolithic serving cache (``llama.init_cache``) allocates
``[slots, max_seq]`` up front: every slot pays worst-case sequence
memory whether it holds a 12-token chat turn or an 8k document, and the
slot extent is welded into the compiled decode step.  This module stores
KV in fixed-size **pages** instead:

- one physical **pool** per cache side, ``[L, P, page_tokens, K*hd]``
  (int8 caches pair it with a ``[L, P, page_tokens, K, 1]`` scale pool
  -- the per-token-per-head scales ride their page);
- a device **page table** ``[B, pages_per_slot] int32`` mapping each
  slot's logical pages to physical pages.  Entry 0 is the reserved
  TRASH page: unallocated logical pages point at it, and inactive
  batch rows route their decode writes there (the paged twin of the
  dense path's ``max_seq - 1`` trash position);
- a host-side :class:`PageAllocator` (free list + per-slot
  assignments).  Admission takes pages as prompts actually need them,
  decode grows a slot page-at-a-time, and eviction returns the slot's
  pages to the pool -- ragged lengths stop forcing worst-case
  allocation, and admit/evict never changes a compiled shape (the pool
  and table shapes are static; only table *values* change).

Device access goes through gather/scatter:
``llama.prefill_into_slot(s)`` / ``decode_step`` / ``decode_loop``
detect a paged cache (:func:`is_paged`) and (a) gather a slot's pages
into the contiguous row view their attention already consumes, (b)
scatter KV writes through the table with per-position
``dynamic_update_slice`` (in-place under donation, same discipline as
the dense path).  The gather materializes the logical view, so the
REFERENCE paged decode streams the cache roughly twice per step on TPU
-- the price of paging without a paged-attention kernel.  ISSUE 11
removed that price on the kernel plane: when the decode backend
resolves to ``paged-kernel`` (ops.decode_backend -- 'auto' past the
flash threshold, or an explicit flash/``decode_kernel`` request),
decode and chunk-verify walk the page table IN-KERNEL
(ops/pallas_decode.py:flash_decode_attention_paged): the BlockSpec
index maps resolve each slot's physical pages from the scalar-
prefetched table, so the logical row view never materializes and the
cache streams once.  The gather path remains the reference (and the
sub-threshold / distributed fallback); the memory win (pool sized to
the *live* token count) and recompile-free admission hold on both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .quant import is_quantized

__all__ = ["PageAllocator", "init_paged_cache", "is_paged",
           "pages_per_slot", "pool_page_tokens", "paged_extent",
           "gather_layer", "gather_slot", "scatter_pages",
           "prefix_page_keys"]


def pages_per_slot(max_seq: int, page_tokens: int) -> int:
    if page_tokens <= 0 or max_seq % page_tokens:
        raise ValueError(
            f"kv_page_tokens={page_tokens}: must divide max_seq "
            f"({max_seq})")
    return max_seq // page_tokens


def init_paged_cache(config, batch: int, max_seq: int | None = None,
                     page_tokens: int = 64,
                     total_pages: int | None = None) -> dict:
    """Paged serving cache: ``{"k": pool, "v": pool, "page_table"}``.

    ``total_pages`` counts PHYSICAL pages including the reserved trash
    page 0 (default: full provisioning, ``batch * pages_per_slot + 1``
    -- memory parity with the dense cache; size it down to serve more
    slots than worst-case memory allows, with the ContinuousBatcher
    preempting under pool pressure)."""
    c = config
    t = max_seq or c.max_seq
    pps = pages_per_slot(t, page_tokens)
    pool_pages = batch * pps + 1 if total_pages is None \
        else int(total_pages)
    if pool_pages < pps + 1:
        raise ValueError(
            f"kv_pages={pool_pages}: the pool must hold at least one "
            f"full slot plus the trash page ({pps + 1})")
    shape = (c.n_layers, pool_pages, page_tokens,
             c.n_kv_heads * c.head_dim)
    if c.kv_dtype == "int8":
        def side():
            return {"int8": jnp.zeros(shape, dtype=jnp.int8),
                    "scale": jnp.zeros(
                        shape[:-1] + (c.n_kv_heads, 1),
                        dtype=jnp.float32)}
    else:
        def side():
            return jnp.zeros(shape, dtype=jnp.dtype(c.dtype))
    return {"k": side(), "v": side(),
            "page_table": jnp.zeros((batch, pps), dtype=jnp.int32)}


def is_paged(cache) -> bool:
    return isinstance(cache, dict) and "page_table" in cache


def _payload(layer):
    return layer["int8"] if is_quantized(layer) else layer


def pool_page_tokens(cache: dict) -> int:
    """Static tokens-per-page of a paged cache's pool."""
    return _payload(cache["k"]).shape[2]


def paged_extent(cache: dict) -> int:
    """Logical per-slot extent (== max_seq) of a paged cache."""
    return cache["page_table"].shape[1] * pool_page_tokens(cache)


def _gather(arr, table):
    """``[P, pt, ...]`` pool -> logical rows via an index-array gather:
    table [B, pps] -> [B, pps*pt, ...]; table [pps] -> [pps*pt, ...].
    Contiguous-minor reshape after the gather, so the result matches
    the dense cache's flat row layout exactly."""
    rows = arr[table]
    lead = table.shape[:-1]
    return rows.reshape(*lead, -1, *arr.shape[2:])


def gather_layer(layer, table):
    """One pool layer (payload or int8 dict) -> the dense flat layer
    view ``[B, T, ...]`` the attention consumers expect."""
    if is_quantized(layer):
        return {"int8": _gather(layer["int8"], table),
                "scale": _gather(layer["scale"], table)}
    return _gather(layer, table)


def scatter_pages(old, new, table, slots, starts, page_tokens: int):
    """Write whole-page prefill rows through the page table: one
    ``dynamic_update_slice`` per (row, covered page).  ``old`` is one
    pool side ``[P, pt, ...]``, ``new`` the page-aligned chunk
    ``[N, S, ...]`` (S a whole number of pages), ``slots``/``starts``
    index ``new``'s rows into the table (scalars may be traced; the
    row/page unroll is static).  Duplicated bucket-pad rows rewrite the
    same physical pages with the same values.  The single shared
    authority for both prefill paths (models/llama.py)."""
    n, s = new.shape[0], new.shape[1]
    for i in range(n):
        for j in range(s // page_tokens):
            page = table[slots[i], starts[i] // page_tokens + j]
            part = jax.lax.dynamic_slice(
                new, (i, j * page_tokens) + (0,) * (new.ndim - 2),
                (1, page_tokens) + new.shape[2:])
            old = jax.lax.dynamic_update_slice(
                old, part, (page, 0) + (0,) * (old.ndim - 2))
    return old


def gather_slot(layer, table_row):
    """One slot's pages -> its contiguous ``[1, T, ...]`` row view."""
    if is_quantized(layer):
        return {"int8": _gather(layer["int8"], table_row)[None],
                "scale": _gather(layer["scale"], table_row)[None]}
    return _gather(layer, table_row)[None]


_PREFIX_SEED = 0x9E3779B97F4A7C15


def prefix_page_keys(tokens, page_tokens: int, limit: int | None = None):
    """Rolling prefix-hash chain for ``tokens``: one key per WHOLE page
    the sequence covers, each key a function of every token up to and
    including that page (so two chains agree exactly on their common
    prefix of identical pages).  ``limit`` caps the number of keys."""
    pt = int(page_tokens)
    pages = len(tokens) // pt
    if limit is not None:
        pages = min(pages, int(limit))
    keys, h = [], _PREFIX_SEED
    for p in range(pages):
        h = hash((h, tuple(tokens[p * pt:(p + 1) * pt])))
        keys.append(h)
    return keys


class PageAllocator:
    """Host-side free list + per-slot page assignments.  Owned by the
    ContinuousBatcher (single-threaded with its step loop); the device
    page table is updated from :attr:`dirty` rows folded into the next
    dispatch, so allocation never costs a device round trip of its
    own.

    Prefix cache (ISSUE 18, ``prefix_cache=True``): prompt-covering
    pages are additionally keyed by a rolling prefix hash of the tokens
    they hold (:func:`prefix_page_keys`).  A later request whose prompt
    starts with the same page chain ADOPTS those physical pages
    read-only -- its table row points at the donor's pages and its
    prefill starts past the shared span.  Correctness rests on KV
    position-determinism: K/V at position ``i`` are a pure function of
    ``(token_i, i)``, so identical tokens at identical positions yield
    byte-identical pages, and a clamped admission chunk re-scattering a
    shared page rewrites it with the very same bytes.  Sharing is
    refcounted per physical page (mapping slots + 1 while indexed);
    "copy-on-write at the first divergent page" means the divergent
    page is simply never mapped -- the adopter allocates a fresh page
    there and prefills it, leaving the donor untouched.  The index
    itself holds a reference, so warm pages survive their slot and
    serve the next request; under pool pressure :meth:`ensure` reclaims
    index-only (refcount-1) entries leaf-first."""

    def __init__(self, total_pages: int, pages_per_slot: int,
                 max_slots: int, prefix_cache: bool = False,
                 prefix_min_tokens: int = 64):
        self.total = int(total_pages)
        self.pps = int(pages_per_slot)
        self.max_slots = int(max_slots)
        # Page 0 is the reserved trash page; ascending hand-out order
        # keeps tests deterministic.
        self._free = list(range(self.total - 1, 0, -1))
        self._slots: dict[int, dict[int, int]] = {}
        # slot -> host table row pending upload (numpy-friendly lists).
        self.dirty: dict[int, list[int]] = {}
        # -- prefix cache ------------------------------------------------
        self.prefix_cache = bool(prefix_cache)
        self.prefix_min_tokens = int(prefix_min_tokens)
        # phys page -> holders (mapping slots, +1 while in the index).
        self._refs: dict[int, int] = {}
        # prefix key -> phys page, insertion order == LRU order (hits
        # and registrations re-insert).  _key_of inverts it for
        # release-time decref; _children drives leaf-first reclaim.
        self._prefix: dict[int, int] = {}
        self._key_of: dict[int, int] = {}
        self._parent: dict[int, int | None] = {}
        self._children: dict[int, int] = {}
        # hit accounting for telemetry/bench (host-side, resettable).
        self.prefix_hits = 0            # pages adopted from the index
        self.prefix_lookups = 0         # whole prompt pages looked up

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_for(self, tokens: int, page_tokens: int) -> int:
        return min(self.pps,
                   -(-max(0, int(tokens)) // int(page_tokens)))

    def holds(self, slot: int) -> int:
        return len(self._slots.get(slot, ()))

    def missing(self, slot: int, pages: int) -> int:
        """How many NEW pages covering logical pages [0, pages) would
        need allocating for ``slot``."""
        owned = self._slots.get(slot, {})
        return sum(1 for logical in range(min(pages, self.pps))
                   if logical not in owned)

    def ensure(self, slot: int, pages: int) -> bool:
        """Allocate (atomically) whatever logical pages [0, pages) the
        slot is missing.  False (and no change) when the free list
        cannot cover them -- after reclaiming unreferenced prefix-index
        entries leaf-first when the cache is on."""
        pages = min(int(pages), self.pps)
        owned = self._slots.setdefault(slot, {})
        wanted = [logical for logical in range(pages)
                  if logical not in owned]
        if len(wanted) > len(self._free):
            self._reclaim(len(wanted) - len(self._free))
        if len(wanted) > len(self._free):
            return False
        if wanted:
            row = self.dirty.setdefault(slot, self._row(slot))
            for logical in wanted:
                phys = self._free.pop()
                owned[logical] = phys
                row[logical] = phys
        return True

    def release(self, slot: int) -> int:
        """Drop the slot's claim on every page it holds (slot finish,
        cancel, eviction) and mark its table row for reset.  Pages the
        prefix index (or another adopter) still references stay
        allocated; the rest return to the free list."""
        owned = self._slots.pop(slot, {})
        if not owned:
            return 0
        freed = []
        for phys in owned.values():
            refs = self._refs.get(phys, 1) - 1
            if refs <= 0:
                self._refs.pop(phys, None)
                self._unindex(phys)
                freed.append(phys)
            else:
                self._refs[phys] = refs
        self._free.extend(sorted(freed, reverse=True))
        self.dirty[slot] = [0] * self.pps
        return len(owned)

    def reset(self) -> None:
        """Forget everything (device state was rebuilt).  The prefix
        index goes too: recover/failover re-initialized the pool, so
        cached page CONTENT no longer exists -- the cache restarts
        cold."""
        self._free = list(range(self.total - 1, 0, -1))
        self._slots.clear()
        self.dirty.clear()
        self._refs.clear()
        self._prefix.clear()
        self._key_of.clear()
        self._parent.clear()
        self._children.clear()

    # -- prefix cache ------------------------------------------------------

    def match_prefix(self, tokens, page_tokens: int) -> int:
        """How many leading WHOLE pages of ``tokens`` the index can
        supply.  Capped one page short of covering the full prompt:
        at least one token must prefill so the first generated token
        has last-position logits to sample from."""
        if not self.prefix_cache \
                or len(tokens) < self.prefix_min_tokens:
            return 0
        limit = min(self.pps, (len(tokens) - 1) // int(page_tokens))
        matched = 0
        for key in prefix_page_keys(tokens, page_tokens, limit):
            if key not in self._prefix:
                break
            matched += 1
        return matched

    def adopt_prefix(self, slot: int, tokens, page_tokens: int) -> int:
        """Map the longest indexed page chain matching ``tokens`` into
        ``slot`` read-only (refcount +1 per page) and return the token
        count covered -- the span admission skips.  The slot must hold
        no pages yet (fresh admission).  Counts lookups/hits for the
        hit-rate metric whenever the cache is consulted."""
        if not self.prefix_cache \
                or len(tokens) < self.prefix_min_tokens:
            return 0
        pt = int(page_tokens)
        limit = min(self.pps, (len(tokens) - 1) // pt)
        self.prefix_lookups += max(0, limit)
        owned = self._slots.setdefault(slot, {})
        if owned:
            return 0
        row = None
        for logical, key in enumerate(
                prefix_page_keys(tokens, pt, limit)):
            phys = self._prefix.get(key)
            if phys is None:
                break
            if row is None:
                row = self.dirty.setdefault(slot, self._row(slot))
            self._refs[phys] = self._refs.get(phys, 1) + 1
            owned[logical] = phys
            row[logical] = phys
            # LRU bump: re-insert at the MRU end.
            self._prefix.pop(key)
            self._prefix[key] = phys
            self.prefix_hits += 1
        return len(owned) * pt

    def register_prefix(self, slot: int, tokens, upto: int,
                        page_tokens: int) -> None:
        """Index every whole page of ``tokens[:upto]`` the slot holds
        (admission progressed to ``upto``).  Indexing a page takes a
        reference, so the content outlives the slot; already-indexed
        pages (including ones this slot adopted) are left alone -- the
        index keeps ONE canonical physical page per prefix key."""
        if not self.prefix_cache \
                or len(tokens) < self.prefix_min_tokens:
            return
        pt = int(page_tokens)
        owned = self._slots.get(slot, {})
        limit = min(self.pps, max(0, int(upto)) // pt,
                    len(tokens) // pt)
        parent = None
        for logical, key in enumerate(
                prefix_page_keys(tokens, pt, limit)):
            phys = owned.get(logical)
            if phys is None:
                break
            held = self._prefix.get(key)
            if held is None and self._key_of.get(phys) is None:
                self._prefix[key] = phys
                self._key_of[phys] = key
                self._refs[phys] = self._refs.get(phys, 1) + 1
                self._parent[phys] = parent
                if parent is not None:
                    self._children[parent] = \
                        self._children.get(parent, 0) + 1
            elif held is not None:
                # LRU bump for the canonical page of this prefix.
                self._prefix.pop(key)
                self._prefix[key] = held
            canonical = held if held is not None else phys
            parent = canonical

    def _unindex(self, phys: int) -> None:
        """Drop ``phys`` from the prefix index (its content is gone or
        its refcount hit zero)."""
        key = self._key_of.pop(phys, None)
        if key is not None:
            self._prefix.pop(key, None)
        parent = self._parent.pop(phys, None)
        if parent is not None and parent in self._children:
            remaining = self._children[parent] - 1
            if remaining <= 0:
                self._children.pop(parent, None)
            else:
                self._children[parent] = remaining
        self._children.pop(phys, None)

    def _reclaim(self, need: int) -> int:
        """Free up to ``need`` pages held ONLY by the prefix index
        (refcount 1), leaf-first in LRU order, so pool pressure evicts
        the cache before it preempts a live slot."""
        if need <= 0 or not self._prefix:
            return 0
        reclaimed = 0
        progress = True
        while reclaimed < need and progress:
            progress = False
            for key, phys in list(self._prefix.items()):
                if self._refs.get(phys, 0) != 1 \
                        or self._children.get(phys, 0):
                    continue            # mapped by a slot, or a parent
                self._refs.pop(phys, None)
                self._unindex(phys)
                self._free.append(phys)
                reclaimed += 1
                progress = True
                if reclaimed >= need:
                    break
        if reclaimed:
            self._free.sort(reverse=True)
        return reclaimed

    def leaked_pages(self) -> int:
        """Allocated pages no slot maps and the index does not hold --
        0 in a healthy allocator (the zero-leak invariant tests
        assert)."""
        live = set()
        for owned in self._slots.values():
            live.update(owned.values())
        live.update(self._key_of)
        return self.total - 1 - len(self._free) - len(live)

    def _row(self, slot: int) -> list[int]:
        row = [0] * self.pps
        for logical, phys in self._slots.get(slot, {}).items():
            row[logical] = phys
        return row

    @property
    def stats(self) -> dict:
        out = {"total": self.total, "free": self.free_pages,
               "held": {slot: len(pages)
                        for slot, pages in self._slots.items()}}
        if self.prefix_cache:
            out["prefix_pages"] = len(self._prefix)
            out["prefix_hits"] = self.prefix_hits
            out["prefix_lookups"] = self.prefix_lookups
        return out
