"""Paged KV cache for continuous-batching LLM serving (ISSUE 8).

The monolithic serving cache (``llama.init_cache``) allocates
``[slots, max_seq]`` up front: every slot pays worst-case sequence
memory whether it holds a 12-token chat turn or an 8k document, and the
slot extent is welded into the compiled decode step.  This module stores
KV in fixed-size **pages** instead:

- one physical **pool** per cache side, ``[L, P, page_tokens, K*hd]``
  (int8 caches pair it with a ``[L, P, page_tokens, K, 1]`` scale pool
  -- the per-token-per-head scales ride their page);
- a device **page table** ``[B, pages_per_slot] int32`` mapping each
  slot's logical pages to physical pages.  Entry 0 is the reserved
  TRASH page: unallocated logical pages point at it, and inactive
  batch rows route their decode writes there (the paged twin of the
  dense path's ``max_seq - 1`` trash position);
- a host-side :class:`PageAllocator` (free list + per-slot
  assignments).  Admission takes pages as prompts actually need them,
  decode grows a slot page-at-a-time, and eviction returns the slot's
  pages to the pool -- ragged lengths stop forcing worst-case
  allocation, and admit/evict never changes a compiled shape (the pool
  and table shapes are static; only table *values* change).

Device access goes through gather/scatter:
``llama.prefill_into_slot(s)`` / ``decode_step`` / ``decode_loop``
detect a paged cache (:func:`is_paged`) and (a) gather a slot's pages
into the contiguous row view their attention already consumes, (b)
scatter KV writes through the table with per-position
``dynamic_update_slice`` (in-place under donation, same discipline as
the dense path).  The gather materializes the logical view, so the
REFERENCE paged decode streams the cache roughly twice per step on TPU
-- the price of paging without a paged-attention kernel.  ISSUE 11
removed that price on the kernel plane: when the decode backend
resolves to ``paged-kernel`` (ops.decode_backend -- 'auto' past the
flash threshold, or an explicit flash/``decode_kernel`` request),
decode and chunk-verify walk the page table IN-KERNEL
(ops/pallas_decode.py:flash_decode_attention_paged): the BlockSpec
index maps resolve each slot's physical pages from the scalar-
prefetched table, so the logical row view never materializes and the
cache streams once.  The gather path remains the reference (and the
sub-threshold / distributed fallback); the memory win (pool sized to
the *live* token count) and recompile-free admission hold on both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .quant import is_quantized

__all__ = ["PageAllocator", "init_paged_cache", "is_paged",
           "pages_per_slot", "pool_page_tokens", "paged_extent",
           "gather_layer", "gather_slot", "scatter_pages"]


def pages_per_slot(max_seq: int, page_tokens: int) -> int:
    if page_tokens <= 0 or max_seq % page_tokens:
        raise ValueError(
            f"kv_page_tokens={page_tokens}: must divide max_seq "
            f"({max_seq})")
    return max_seq // page_tokens


def init_paged_cache(config, batch: int, max_seq: int | None = None,
                     page_tokens: int = 64,
                     total_pages: int | None = None) -> dict:
    """Paged serving cache: ``{"k": pool, "v": pool, "page_table"}``.

    ``total_pages`` counts PHYSICAL pages including the reserved trash
    page 0 (default: full provisioning, ``batch * pages_per_slot + 1``
    -- memory parity with the dense cache; size it down to serve more
    slots than worst-case memory allows, with the ContinuousBatcher
    preempting under pool pressure)."""
    c = config
    t = max_seq or c.max_seq
    pps = pages_per_slot(t, page_tokens)
    pool_pages = batch * pps + 1 if total_pages is None \
        else int(total_pages)
    if pool_pages < pps + 1:
        raise ValueError(
            f"kv_pages={pool_pages}: the pool must hold at least one "
            f"full slot plus the trash page ({pps + 1})")
    shape = (c.n_layers, pool_pages, page_tokens,
             c.n_kv_heads * c.head_dim)
    if c.kv_dtype == "int8":
        def side():
            return {"int8": jnp.zeros(shape, dtype=jnp.int8),
                    "scale": jnp.zeros(
                        shape[:-1] + (c.n_kv_heads, 1),
                        dtype=jnp.float32)}
    else:
        def side():
            return jnp.zeros(shape, dtype=jnp.dtype(c.dtype))
    return {"k": side(), "v": side(),
            "page_table": jnp.zeros((batch, pps), dtype=jnp.int32)}


def is_paged(cache) -> bool:
    return isinstance(cache, dict) and "page_table" in cache


def _payload(layer):
    return layer["int8"] if is_quantized(layer) else layer


def pool_page_tokens(cache: dict) -> int:
    """Static tokens-per-page of a paged cache's pool."""
    return _payload(cache["k"]).shape[2]


def paged_extent(cache: dict) -> int:
    """Logical per-slot extent (== max_seq) of a paged cache."""
    return cache["page_table"].shape[1] * pool_page_tokens(cache)


def _gather(arr, table):
    """``[P, pt, ...]`` pool -> logical rows via an index-array gather:
    table [B, pps] -> [B, pps*pt, ...]; table [pps] -> [pps*pt, ...].
    Contiguous-minor reshape after the gather, so the result matches
    the dense cache's flat row layout exactly."""
    rows = arr[table]
    lead = table.shape[:-1]
    return rows.reshape(*lead, -1, *arr.shape[2:])


def gather_layer(layer, table):
    """One pool layer (payload or int8 dict) -> the dense flat layer
    view ``[B, T, ...]`` the attention consumers expect."""
    if is_quantized(layer):
        return {"int8": _gather(layer["int8"], table),
                "scale": _gather(layer["scale"], table)}
    return _gather(layer, table)


def scatter_pages(old, new, table, slots, starts, page_tokens: int):
    """Write whole-page prefill rows through the page table: one
    ``dynamic_update_slice`` per (row, covered page).  ``old`` is one
    pool side ``[P, pt, ...]``, ``new`` the page-aligned chunk
    ``[N, S, ...]`` (S a whole number of pages), ``slots``/``starts``
    index ``new``'s rows into the table (scalars may be traced; the
    row/page unroll is static).  Duplicated bucket-pad rows rewrite the
    same physical pages with the same values.  The single shared
    authority for both prefill paths (models/llama.py)."""
    n, s = new.shape[0], new.shape[1]
    for i in range(n):
        for j in range(s // page_tokens):
            page = table[slots[i], starts[i] // page_tokens + j]
            part = jax.lax.dynamic_slice(
                new, (i, j * page_tokens) + (0,) * (new.ndim - 2),
                (1, page_tokens) + new.shape[2:])
            old = jax.lax.dynamic_update_slice(
                old, part, (page, 0) + (0,) * (old.ndim - 2))
    return old


def gather_slot(layer, table_row):
    """One slot's pages -> its contiguous ``[1, T, ...]`` row view."""
    if is_quantized(layer):
        return {"int8": _gather(layer["int8"], table_row)[None],
                "scale": _gather(layer["scale"], table_row)[None]}
    return _gather(layer, table_row)[None]


class PageAllocator:
    """Host-side free list + per-slot page assignments.  Owned by the
    ContinuousBatcher (single-threaded with its step loop); the device
    page table is updated from :attr:`dirty` rows folded into the next
    dispatch, so allocation never costs a device round trip of its
    own."""

    def __init__(self, total_pages: int, pages_per_slot: int,
                 max_slots: int):
        self.total = int(total_pages)
        self.pps = int(pages_per_slot)
        self.max_slots = int(max_slots)
        # Page 0 is the reserved trash page; ascending hand-out order
        # keeps tests deterministic.
        self._free = list(range(self.total - 1, 0, -1))
        self._slots: dict[int, dict[int, int]] = {}
        # slot -> host table row pending upload (numpy-friendly lists).
        self.dirty: dict[int, list[int]] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_for(self, tokens: int, page_tokens: int) -> int:
        return min(self.pps,
                   -(-max(0, int(tokens)) // int(page_tokens)))

    def holds(self, slot: int) -> int:
        return len(self._slots.get(slot, ()))

    def missing(self, slot: int, pages: int) -> int:
        """How many NEW pages covering logical pages [0, pages) would
        need allocating for ``slot``."""
        owned = self._slots.get(slot, {})
        return sum(1 for logical in range(min(pages, self.pps))
                   if logical not in owned)

    def ensure(self, slot: int, pages: int) -> bool:
        """Allocate (atomically) whatever logical pages [0, pages) the
        slot is missing.  False (and no change) when the free list
        cannot cover them."""
        pages = min(int(pages), self.pps)
        owned = self._slots.setdefault(slot, {})
        wanted = [logical for logical in range(pages)
                  if logical not in owned]
        if len(wanted) > len(self._free):
            return False
        if wanted:
            row = self.dirty.setdefault(slot, self._row(slot))
            for logical in wanted:
                phys = self._free.pop()
                owned[logical] = phys
                row[logical] = phys
        return True

    def release(self, slot: int) -> int:
        """Return every page the slot holds to the pool (slot finish,
        cancel, eviction) and mark its table row for reset."""
        owned = self._slots.pop(slot, {})
        if not owned:
            return 0
        self._free.extend(sorted(owned.values(), reverse=True))
        self.dirty[slot] = [0] * self.pps
        return len(owned)

    def reset(self) -> None:
        """Forget everything (device state was rebuilt)."""
        self._free = list(range(self.total - 1, 0, -1))
        self._slots.clear()
        self.dirty.clear()

    def _row(self, slot: int) -> list[int]:
        row = [0] * self.pps
        for logical, phys in self._slots.get(slot, {}).items():
            row[logical] = phys
        return row

    @property
    def stats(self) -> dict:
        return {"total": self.total, "free": self.free_pages,
                "held": {slot: len(pages)
                         for slot, pages in self._slots.items()}}
