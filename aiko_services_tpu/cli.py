"""Command-line tools (reference: ``aiko_pipeline`` / ``aiko_registrar`` /
``aiko_dashboard`` console scripts, src/aiko_services/main/pipeline.py:
1826-2034, registrar.py:358, dashboard.py:771-790).

No pip entry points are assumed; everything runs via::

    python -m aiko_services_tpu registrar
    python -m aiko_services_tpu pipeline create def.json -fd "(x: 1)"
    python -m aiko_services_tpu pipeline list
    python -m aiko_services_tpu pipeline destroy NAME
    python -m aiko_services_tpu recorder | storage | dashboard
"""

from __future__ import annotations

import json
import os
import sys

import click

from .runtime import init_process
from .utils import get_logger

_logger = get_logger("aiko.cli")


def _runtime(transport: str | None):
    runtime = init_process(transport=transport)
    runtime.initialize()
    return runtime


_transport_option = click.option(
    "--transport", "-t", default=None,
    help="message fabric: mqtt | loopback (default: $AIKO_TRANSPORT)")

_HOOK_ALIASES = {"pf": "pipeline.process_frame:0",
                 "pe": "pipeline.process_element:0",
                 "pep": "pipeline.process_element_post:0",
                 "ps": "pipeline.process_segment:0",
                 "psp": "pipeline.process_segment_post:0",
                 "pst": "pipeline.process_stage:0",
                 "pstp": "pipeline.process_stage_post:0",
                 "hop": "pipeline.stage_hop:0",
                 "rp": "pipeline.replacement:0"}


def _parse_hooks_spec(hooks_spec: str | None) -> list[str]:
    if not hooks_spec:
        return []
    wanted = {part.strip() for part in hooks_spec.split(",")}
    unknown = wanted - set(_HOOK_ALIASES) - {"all"}
    if unknown:
        raise click.BadParameter(
            f"unknown hooks {sorted(unknown)}; "
            f"choose from {sorted(_HOOK_ALIASES)} or 'all'")
    if "all" in wanted:
        return list(_HOOK_ALIASES.values())
    return [_HOOK_ALIASES[part] for part in wanted]


@click.group()
def main():
    """aiko_services_tpu command line."""


# -- registrar --------------------------------------------------------------

@main.command()
@_transport_option
def registrar(transport):
    """Run a Registrar (discovery directory + primary election)."""
    from .services import Registrar

    runtime = _runtime(transport)
    Registrar(runtime=runtime)
    runtime.run()


# -- recorder / storage -----------------------------------------------------

@main.command()
@_transport_option
def recorder(transport):
    """Run a Recorder (namespace-wide log aggregation)."""
    from .services import Recorder

    runtime = _runtime(transport)
    Recorder(runtime=runtime)
    runtime.run()


@main.command()
@_transport_option
@click.option("--database", "-d", default="aiko_storage.db",
              help="sqlite database path")
def storage(transport, database):
    """Run a Storage actor (persistent key/value)."""
    from .services import Storage

    runtime = _runtime(transport)
    Storage(database_path=database, runtime=runtime)
    runtime.run()


# -- pipeline ---------------------------------------------------------------

@main.group()
def pipeline():
    """Create / list / destroy dataflow pipelines."""


@pipeline.command("create")
@click.argument("definition_pathname")
@_transport_option
@click.option("--name", "-n", default=None, help="override pipeline name")
@click.option("--stream-id", "-s", default=None,
              help="create a stream with this id at startup")
@click.option("--frame-data", "-fd", default=None,
              help="frame data for the startup stream, e.g. '(x: 1)'")
@click.option("--parameter", "-p", "parameters", nargs=2, multiple=True,
              help="stream parameter NAME VALUE (repeatable)")
@click.option("--frame-rate", "-fr", default=0.0,
              help="frame generator rate limit (frames/sec, 0 = max)")
@click.option("--profile", "profile_dir", default=None,
              help="write a jax.profiler trace (TensorBoard/xprof) to DIR "
                   "with per-element TraceAnnotations while running")
@click.option("--hooks", "hooks_spec", default=None,
              help="attach the default printing handler to hooks: "
                   "comma list of pf,pe,pep,rp,all (reference "
                   "pipeline.py:1613-1625)")
@click.option("--metrics-port", default=None, type=int,
              help="serve the telemetry plane over HTTP on this port "
                   "(0 = assigned): /metrics Prometheus text, /traces "
                   "recent distributed frame traces")
@click.option("--metrics-host", default="127.0.0.1",
              help="bind address for --metrics-port (default loopback; "
                   "0.0.0.0 opts into remote scraping)")
@click.option("--fault-plan", "fault_plan", default=None,
              help="arm a chaos FaultPlan at startup: inline JSON or "
                   "@path/to/plan.json (see README 'Failure model'); "
                   "arm/disarm a RUNNING pipeline with "
                   "'pipeline update NAME -p fault_plan <json|off>'")
@click.option("--check", "strict_preflight", is_flag=True,
              help="strict pre-flight: refuse to start on lint "
                   "WARNINGS too (overrides the definition's "
                   "'preflight' parameter, including 'off')")
def pipeline_create(definition_pathname, transport, name, stream_id,
                    frame_data, parameters, frame_rate, profile_dir,
                    hooks_spec, metrics_port, metrics_host, fault_plan,
                    strict_preflight):
    """Create a Pipeline from DEFINITION_PATHNAME (JSON) and run it."""
    from .pipeline import create_pipeline
    from .utils import parse_value

    hook_names = _parse_hooks_spec(hooks_spec)   # fail before building
    if fault_plan and fault_plan.startswith("@"):
        try:
            with open(fault_plan[1:]) as fh:
                fault_plan = fh.read()
        except OSError as error:
            raise click.BadParameter(f"--fault-plan: {error}")
    if fault_plan:
        from .faults import FaultPlan
        try:                                     # fail before building
            FaultPlan.parse(fault_plan)
        except (ValueError, TypeError) as error:
            raise click.BadParameter(f"--fault-plan: {error}")
    runtime = _runtime(transport)
    instance = create_pipeline(
        definition_pathname, name=name, runtime=runtime,
        preflight="strict" if strict_preflight else None)
    if fault_plan:
        instance.arm_faults(fault_plan)
    if hook_names:
        from .runtime.hooks import default_hook_handler

        for hook_name in hook_names:
            instance.add_hook_handler(hook_name, default_hook_handler)
    metrics_server = None
    if metrics_port is not None:
        from .observability import MetricsServer

        if instance.telemetry is None:
            raise click.ClickException(
                "--metrics-port needs telemetry, but the definition "
                "sets 'telemetry: off'")
        metrics_server = MetricsServer(instance, metrics_port,
                                       host=metrics_host)
        click.echo(f"metrics on {metrics_host}:{metrics_server.port}"
                   f"/metrics (traces on /traces)")
    profiler = None
    if profile_dir:
        from .tpu import Profiler

        profiler = Profiler()
        profiler.start(profile_dir)
        profiler.attach(instance)
    try:
        if stream_id is not None or frame_data is not None:
            stream_parameters = {key: value for key, value in parameters}
            if frame_rate:
                stream_parameters["rate"] = frame_rate
            stream = instance.create_stream_local(stream_id or "1",
                                                  stream_parameters)
            if stream is None:
                raise click.ClickException(
                    f"stream {stream_id or '1'} rejected at start "
                    "(element start_stream failed; see log)")
            if frame_data:
                data = parse_value(frame_data)
                if not isinstance(data, dict):
                    raise click.BadParameter(
                        "frame data must be an S-expression dictionary, "
                        "e.g. '(x: 1)'")
                instance.create_frame_local(stream, data)
        # A drained pipeline retires its process: the rolling-restart
        # driver (and any supervisor) respawns it fresh (ISSUE 13).
        runtime.run(until=lambda: instance.share.get("drained"))
        if instance.share.get("drained"):
            click.echo("pipeline drained; exiting")
    finally:
        if profiler is not None:
            profiler.detach()
            profiler.stop()
        if metrics_server is not None:
            metrics_server.stop()


@pipeline.command("list")
@_transport_option
@click.option("--timeout", default=3.0, help="discovery wait seconds")
def pipeline_list(transport, timeout):
    """List pipelines registered in the namespace directory."""
    from .pipeline import PROTOCOL_PIPELINE
    from .services import ServiceFilter
    from .services.share import services_cache_singleton

    runtime = _runtime(transport)
    cache = services_cache_singleton(runtime)
    runtime.run(until=lambda: cache.state == "ready", timeout=timeout)
    records = cache.registry.query(
        ServiceFilter(protocol=PROTOCOL_PIPELINE))
    if cache.state != "ready":
        click.echo("warning: no registrar found", err=True)
    for record in records:
        click.echo(f"{record.topic_path}  {record.name}  "
                   f"tags={','.join(record.tags)}")
    click.echo(f"{len(records)} pipeline(s)")


def _with_named_pipeline(name, transport, timeout, action, verb):
    """Discover ONE pipeline by name and run ``action(proxy)`` against
    it (shared by destroy/update; the next named-pipeline command should
    use this too)."""
    from .pipeline import PROTOCOL_PIPELINE
    from .services import ServiceFilter, do_command

    runtime = _runtime(transport)
    done = []

    def run_action(proxy):
        action(runtime, proxy)
        done.append(proxy.topic_path)

    do_command(runtime, None,
               ServiceFilter(name=name, protocol=PROTOCOL_PIPELINE),
               run_action)
    runtime.run(until=lambda: bool(done), timeout=timeout)
    if done:
        click.echo(f"{verb} sent to {done[0]}")
    else:
        click.echo(f"pipeline {name!r} not found", err=True)
        sys.exit(1)


@pipeline.command("destroy")
@click.argument("name")
@_transport_option
@click.option("--timeout", default=3.0, help="discovery wait seconds")
def pipeline_destroy(name, transport, timeout):
    """Ask the named pipeline process to stop."""
    _with_named_pipeline(name, transport, timeout,
                         lambda runtime, proxy: proxy.stop(), "stop")


@pipeline.command("update")
@click.argument("name")
@_transport_option
@click.option("--parameter", "-p", "parameters", nargs=2, multiple=True,
              help="update a live parameter NAME VALUE (repeatable); "
                   "qualified 'Element.param' targets that element")
@click.option("--stream-id", "-s", default=None,
              help="stream id for --frame-data (created on demand)")
@click.option("--frame-data", "-fd", default=None,
              help="inject a frame, e.g. '(x: 1)'")
@click.option("--timeout", default=3.0, help="discovery wait seconds")
def pipeline_update(name, transport, parameters, stream_id, frame_data,
                    timeout):
    """Live-update a running pipeline found by NAME: set parameters
    (``set_parameter`` routes qualified names to the element) and/or
    inject a frame (reference ``aiko_pipeline update``,
    pipeline.py:1982-2034)."""
    from .utils import parse_value

    if not parameters and frame_data is None:
        raise click.UsageError("nothing to update: pass -p and/or -fd")
    data = None
    if frame_data is not None:
        data = parse_value(frame_data)
        if not isinstance(data, dict):
            raise click.BadParameter(
                "frame data must be an S-expression dictionary, "
                "e.g. '(x: 1)'")

    def send_update(runtime, proxy):
        # RemoteProxy encodes the wire format; these become
        # "(set_parameter k v)" / "(process_frame (stream_id: ..) ..)"
        # on the pipeline's in-topic.
        for key, value in parameters:
            proxy.set_parameter(key, value)
        if data is not None:
            proxy.process_frame({"stream_id": stream_id or "1"}, data)

    _with_named_pipeline(name, transport, timeout, send_update, "update")


@pipeline.command("drain")
@click.argument("name")
@_transport_option
@click.option("--timeout", default=3.0, help="discovery wait seconds")
def pipeline_drain(name, transport, timeout):
    """Cooperatively drain the named pipeline (ISSUE 13): admission
    stops, in-flight work finishes or parks in the durable journal,
    then the service announces its death so a peer adopts its streams
    -- zero frame drop.  Requires ``journal: on`` for the handoff to
    carry state."""
    _with_named_pipeline(name, transport, timeout,
                         lambda runtime, proxy: proxy.drain(), "drain")


@pipeline.command("restart")
@click.option("--name", default="*",
              help="pipeline name to restart (default: every pipeline)")
@_transport_option
@click.option("--rolling", is_flag=True, required=True,
              help="drain pipelines ONE AT A TIME, waiting for each "
                   "to hand off and exit before touching the next -- "
                   "with journaled streams and a peer to adopt them, "
                   "a zero-frame-drop fleet restart (weight swaps "
                   "included)")
@click.option("--timeout", default=30.0,
              help="seconds to wait for each drain to complete")
def pipeline_restart(name, transport, rolling, timeout):
    """Rolling restart: drain each matching pipeline in sequence.
    Each drain parks undelivered work in the journal and exits; the
    gateway re-binds its sessions to a surviving peer, which adopts
    the journal -- so the fleet serves through the whole walk.  Your
    supervisor (systemd/k8s/the chaos driver) restarts the drained
    processes; the refreshed instance rejoins the peer pool and the
    next drain can hand off to it."""
    import time as time_module

    from .pipeline import PROTOCOL_PIPELINE
    from .services import ServiceFilter
    from .services.share import services_cache_singleton

    runtime = _runtime(transport)
    cache = services_cache_singleton(runtime)
    runtime.run(until=lambda: cache.state == "ready", timeout=5.0)
    service_filter = ServiceFilter(protocol=PROTOCOL_PIPELINE) \
        if name == "*" else ServiceFilter(name=name,
                                          protocol=PROTOCOL_PIPELINE)
    records = cache.registry.query(service_filter)
    if not records:
        click.echo(f"no pipelines matching {name!r}", err=True)
        sys.exit(1)
    all_pipelines = ServiceFilter(protocol=PROTOCOL_PIPELINE)

    def peers_of(record):
        return [entry for entry in
                cache.registry.query(all_pipelines)
                if entry.topic_path != record.topic_path]

    walked = 0
    for record in records:
        if not peers_of(record):
            # Draining the last live pipeline strands its sessions
            # and leaves its journal unadopted -- refuse, like
            # replay_limit refuses unbounded replays.
            click.echo(f"  refusing to drain {record.name}: no live "
                       f"peer to adopt its streams (respawn one "
                       f"first)", err=True)
            continue
        click.echo(f"draining {record.name} ({record.topic_path})")
        runtime.message.publish(f"{record.topic_path}/in", "(drain)")
        deadline = time_module.monotonic() + timeout
        gone = lambda: cache.registry.get(record.topic_path) is None
        runtime.run(until=gone,
                    timeout=max(0.1, deadline - time_module.monotonic()))
        if not gone():
            click.echo(f"  {record.name} still serving after "
                       f"{timeout:.0f}s (journal off, or frames "
                       f"wedged past drain_timeout_ms)", err=True)
            continue
        walked += 1
        click.echo(f"  {record.name} drained and retired")
        # Wait for the supervisor's respawn to REJOIN before touching
        # the next pipeline: draining onward while the fleet is a
        # peer short risks a no-survivor handoff at the next step.
        rejoined = lambda: any(
            entry.name == record.name for entry in
            cache.registry.query(all_pipelines))
        runtime.run(until=rejoined, timeout=timeout)
        if rejoined():
            click.echo(f"  {record.name} respawned and rejoined")
        else:
            click.echo(f"  warning: no respawn of {record.name} "
                       f"within {timeout:.0f}s; continuing (next "
                       f"drain is refused unless a peer remains)",
                       err=True)
    click.echo(f"rolling restart: {walked}/{len(records)} "
               f"pipeline(s) walked")


@pipeline.command("validate")
@click.argument("definition_pathname")
def pipeline_validate(definition_pathname):
    """Parse + schema-check a pipeline definition without running it."""
    from .pipeline import load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    click.echo(json.dumps(
        {"name": definition.name,
         "graph": definition.graph,
         "elements": definition.element_names()}, indent=2))


# -- static analysis --------------------------------------------------------

@main.command("lint")
@click.argument("paths", nargs=-1)
@click.option("--self", "self_check", is_flag=True,
              help="run the framework self-check rules over the "
                   "aiko_services_tpu sources (hook parity, span sync, "
                   "resume-post identity, parameter registry)")
@click.option("--strict", is_flag=True,
              help="exit 1 on warnings too (the `pipeline create "
                   "--check` gate)")
@click.option("--rules", "list_rules", is_flag=True,
              help="print the rule catalogue and exit")
def lint(paths, self_check, strict, list_rules):
    """aiko_lint: static dataflow, residency, and contract analysis.

    PATHS are pipeline definitions (.json) and/or element sources
    (.py files or directories).  Definitions get the dataflow +
    residency layers (exactly what `pipeline create` pre-flights);
    element sources get the residency rules standalone.  Exit 0 clean,
    1 on error findings (or any finding under --strict).
    """
    from .analysis import RULES, run_lint

    if list_rules:
        for rule, (severity, description) in RULES.items():
            click.echo(f"{rule:24} {severity:8} {description}")
        return
    if not paths and not self_check:
        raise click.UsageError(
            "nothing to lint: pass definition/source paths, --self, "
            "or --rules")
    sys.exit(run_lint(paths, self_check=self_check, strict=strict,
                      echo=click.echo))


# -- gateway load generator --------------------------------------------------

@main.command("loadgen")
@click.option("--host", default=None,
              help="target a RUNNING gateway at this host (with "
                   "--port); default builds a self-contained 2-stage "
                   "pipeline + gateway on loopback")
@click.option("--port", default=None, type=int,
              help="target gateway port (with --host)")
@click.option("--rate", default=25.0,
              help="interactive tenant arrival rate, frames/sec "
                   "(open loop)")
@click.option("--overload", default=2.0,
              help="batch tenants' combined rate as a multiple of "
                   "--rate (2.0 = 2x overload pressure)")
@click.option("--frames", default=100,
              help="frames per tenant")
@click.option("--deadline-ms", default=0.0,
              help="per-frame deadline for the interactive tenant "
                   "(0 = none)")
@click.option("--busy-ms", default=5.0,
              help="self-contained mode: per-stage busy time")
def loadgen(host, port, rate, overload, frames, deadline_ms, busy_ms):
    """Open-loop mixed-tenant load against a gateway: an interactive
    tenant at --rate plus a batch tenant at --rate * --overload,
    per-class p50/p99/goodput and per-tenant shed/reject counts as
    JSON (the same generator bench_pipeline_gateway drives)."""
    import json as json_module
    import threading

    from .gateway.loadgen import LoadSpec, run_loadgen

    specs = [
        LoadSpec("alice", "interactive", rate, int(frames),
                 data={"x": [1.0] * 16},
                 deadline_ms=deadline_ms or 0.0),
        LoadSpec("bulk", "batch", rate * overload,
                 int(frames * overload), data={"x": [1.0] * 16}),
    ]
    if host is not None and port is not None:
        click.echo(json_module.dumps(run_loadgen(host, port, specs),
                                     indent=2))
        return
    if (host is None) != (port is None):
        raise click.UsageError("--host and --port go together")
    from .pipeline import Pipeline

    runtime = _runtime("loopback")

    def stage(name):
        return {"name": name, "input": [{"name": "x"}],
                "output": [{"name": "x"}],
                "parameters": {"busy_ms": busy_ms, "factor": 2.0},
                "placement": {"devices": "auto"},
                "deploy": {"local": {
                    "module": "aiko_services_tpu.elements.common",
                    "class_name": "StageWork"}}}

    instance = Pipeline(
        {"version": 0, "name": "loadgen", "runtime": "jax",
         "graph": ["(detect llm)"],
         "parameters": {
             "gateway": "on",
             "qos": {"classes": {"batch": {"device_inflight": 1}},
                     "tenants": {
                         "alice": {"class": "interactive",
                                   "budget": 64},
                         "bulk": {"class": "batch", "budget": 16}},
                     "max_inflight": 64}},
         "elements": [stage("detect"), stage("llm")]},
        runtime=runtime)
    report: list = []

    def drive():
        try:
            report.append(run_loadgen("127.0.0.1",
                                      instance.gateway.port, specs))
        finally:
            runtime.engine.terminate()

    threading.Thread(target=drive, daemon=True,
                     name="loadgen-driver").start()
    runtime.run()
    if report:
        click.echo(json_module.dumps(report[0], indent=2))


# -- fleet observability -----------------------------------------------------

@main.command("fleet")
@_transport_option
@click.option("--member", "members", multiple=True,
              help="static host:port scrape target (repeatable; "
                   "additive with registrar discovery)")
@click.option("--scrape-ms", default=None, type=float,
              help="scrape cadence (default: 1000)")
@click.option("--interval", default=2.0,
              help="seconds between terminal renders")
@click.option("--once", is_flag=True,
              help="one scrape sweep, one render, exit")
def fleet(transport, members, scrape_ms, interval, once):
    """Run a standalone fleet collector: registrar-discovered members
    (the ``metrics=`` / ``gateway=`` tags pipelines bind) plus any
    ``--member`` targets, scraped at ``/metrics/raw``, merged exactly,
    rendered as a terminal view.  jax-free -- runs anywhere."""
    import threading
    import time as time_module

    from .observability.fleet import (FLEET_SCRAPE_MS_DEFAULT,
                                      FleetCollector)

    cadence = scrape_ms if scrape_ms is not None \
        else FLEET_SCRAPE_MS_DEFAULT
    if once and members:
        # Static targets need no fabric at all: sweep, render, exit.
        collector = FleetCollector(scrape_ms=0, members=members)
        collector.scrape_once()
        click.echo(collector.render_terminal())
        return
    runtime = _runtime(transport)
    collector = FleetCollector(runtime=runtime, scrape_ms=cadence,
                               members=members)
    collector.start()

    def render_loop():
        try:
            if once:
                # Give discovery one beat to populate, then one sweep.
                time_module.sleep(max(interval, 0.5))
                collector.scrape_once()
                click.echo(collector.render_terminal())
                return
            while True:
                time_module.sleep(interval)
                click.echo(collector.render_terminal())
                click.echo("")
        finally:
            if once:
                runtime.engine.terminate()

    threading.Thread(target=render_loop, daemon=True,
                     name="fleet-render").start()
    runtime.run()


# -- critical-path explain (offline) ----------------------------------------

@main.command("explain")
@click.argument("path")
@click.option("--frame", "frame_id", type=int, default=None,
              help="restrict the timeline to ONE frame id (default: "
                   "the dump's trigger frame, or everything)")
@click.option("--stream", "stream_id", default=None,
              help="with --frame: the frame's stream id")
def explain(path, frame_id, stream_id):
    """Render a black-box dump or a saved trace offline: the causal
    timeline plus the critical-path bucket table (where did the
    frame's time go).

    PATH is a ``blackbox_*.json`` dump (written under the pipeline's
    ``blackbox_dir`` on deadline miss / replay / breaker open /
    replica failover / stream error), a single trace from
    ``GET /traces/<id>``, or a ``GET /traces`` / ``GET /explain``
    body saved to disk.  jax-free -- runs anywhere the dump landed.
    """
    from .observability import render_buckets, render_timeline
    from .observability.critical_path import attribute_events

    try:
        payload = json.loads(open(path).read())
    except (OSError, ValueError) as error:
        raise click.ClickException(f"cannot read {path}: {error}")

    if isinstance(payload, dict) \
            and isinstance(payload.get("events"), list):
        # Black-box dump: ring tail + in-flight frame states.  The
        # list check discriminates against a saved /explain?frame=
        # body, whose "events" key is an int COUNT, not the ring.
        click.echo(f"black box: {payload.get('reason', '?')} in "
                   f"pipeline {payload.get('pipeline', '?')} "
                   f"(stream {payload.get('stream')}, frame "
                   f"{payload.get('frame')})")
        if payload.get("detail"):
            click.echo(f"  {payload['detail']}")
        target = frame_id if frame_id is not None \
            else payload.get("frame")
        target_stream = stream_id if stream_id is not None \
            else payload.get("stream")
        raw = payload["events"]
        if target is not None:
            from .observability import select_frame_events
            known = {"t", "type", "stream", "frame", "name", "ms"}
            events = [(entry.get("t", 0.0), entry.get("type", "?"),
                       entry.get("stream"), entry.get("frame"),
                       entry.get("name"), entry.get("ms"),
                       {key: value for key, value in entry.items()
                        if key not in known} or None)
                      for entry in raw]
            # Same stale-same-id discipline as the live engine: the
            # dump's ring tail can span a destroyed stream AND its
            # recreated same-id successor -- only the newest
            # incarnation's frame events form one causal timeline.
            events = select_frame_events(events, target, target_stream)
            click.echo(f"\ntimeline for frame {target} "
                       f"({len(events)} event(s)):")
            report = attribute_events(events)
            for line in render_timeline(report["timeline"]):
                click.echo("  " + line)
            click.echo("\nattribution:")
            for line in render_buckets(report):
                click.echo("  " + line)
        else:
            # No trigger frame (e.g. a replica_failover dump): the
            # ring tail interleaves MANY frames, and the single-frame
            # state machine would bill one frame's waits to another's
            # compute -- render the raw interleaved timeline instead
            # (shared renderer, each line tagged with its frame) and
            # point at --frame for per-frame attribution.  The dump's
            # entries are already ``events_as_dicts`` output: reshape
            # in place, no tuple round trip.
            click.echo(f"\ninterleaved timeline "
                       f"({len(raw)} event(s)):")
            base = raw[0].get("t", 0.0) if raw else 0.0
            timeline = []
            for entry in raw:
                line_entry = dict(entry)
                line_entry["t_ms"] = round(
                    (line_entry.pop("t", 0.0) - base) * 1000.0, 3)
                frame = line_entry.pop("frame", None)
                stream = line_entry.pop("stream", None)
                if frame is not None:
                    line_entry["at"] = f"{stream}/{frame}"
                timeline.append(line_entry)
            for line in render_timeline(timeline):
                click.echo("  " + line)
            frames_seen = sorted(
                {(str(entry.get("stream")), entry.get("frame"))
                 for entry in raw if entry.get("frame") is not None})
            if frames_seen:
                click.echo(
                    "\nper-frame attribution: re-run with --frame N "
                    "[--stream S]; frames on this timeline: "
                    + ", ".join(f"{s}/{f}" for s, f in frames_seen))
        frames = payload.get("frames") or []
        if frames:
            click.echo(f"\nin-flight frames at dump time "
                       f"({len(frames)}):")
            for state in frames:
                where = state.get("paused") or state.get("waiting") \
                    or state.get("stage") or "walking"
                click.echo(f"  stream {state.get('stream')} frame "
                           f"{state.get('frame')}: at {where}, "
                           f"replays={state.get('replays', 0)}, "
                           f"age={state.get('age_s', 0)}s")
        return

    if isinstance(payload, dict) \
            and isinstance(payload.get("timeline"), list):
        # Saved /explain?frame= body (its "events" key is a COUNT).
        click.echo(f"frame {payload.get('frame')} "
                   f"(stream {payload.get('stream')}):")
        for line in render_timeline(payload["timeline"]):
            click.echo("  " + line)
        if payload.get("buckets"):
            click.echo("\nattribution:")
            for line in render_buckets(payload):
                click.echo("  " + line)
        return

    # Trace shapes: one trace, a /traces body, or an /explain report.
    traces = []
    if isinstance(payload, dict) and "spans" in payload:
        traces = [payload]
    elif isinstance(payload, dict) and "traces" in payload:
        traces = payload["traces"]
    if traces:
        if frame_id is not None:
            traces = [t for t in traces
                      if any(s.get("frame") == frame_id
                             for s in t.get("spans", []))]
        for trace in traces:
            click.echo(f"trace {trace.get('trace_id')} "
                       f"({'ok' if trace.get('okay') else 'ERROR'}):")
            spans = sorted(trace.get("spans", []),
                           key=lambda s: s.get("start", 0.0))
            base = spans[0].get("start", 0.0) if spans else 0.0
            for span in spans:
                offset = (span.get("start", 0.0) - base) * 1000.0
                click.echo(f"  +{offset:10.3f} ms  "
                           f"{span.get('kind', '?'):8} "
                           f"{span.get('name', '?'):28} "
                           f"{span.get('duration_ms', 0.0):10.3f} ms  "
                           f"{span.get('status', '')}")
            if trace.get("buckets"):
                click.echo("  attribution:")
                for line in render_buckets(trace):
                    click.echo("    " + line)
        return
    if isinstance(payload, dict) and "buckets" in payload:
        click.echo(f"aggregate over {payload.get('frames', '?')} "
                   f"frame(s):")
        for line in render_buckets(payload):
            click.echo("  " + line)
        for entry in payload.get("top", []):
            click.echo(f"  top: {entry.get('stage')}:"
                       f"{entry.get('bucket')} {entry.get('ms')} ms")
        return
    raise click.ClickException(
        "unrecognized payload: expected a blackbox_*.json dump, a "
        "trace, a /traces body, or an /explain report")


# -- weight conversion ------------------------------------------------------

@main.group()
def convert():
    """Ingest pretrained weights (HF safetensors -> framework orbax)."""


@convert.command("llama")
@click.argument("source")
@click.argument("destination")
@click.option("--max-seq", default=8192, help="serving context length")
def convert_llama_cmd(source, destination, max_seq):
    """Convert an HF Llama safetensors file/dir to an orbax checkpoint.

    Afterwards: pipeline elements load it via the ``checkpoint``
    parameter; ``LLMService(checkpoint=DESTINATION)`` serves it.
    """
    from .models.convert import convert_llama

    config = convert_llama(source, destination, max_seq=max_seq)
    click.echo(json.dumps({"destination": destination,
                           "config": config.__dict__}))


@convert.command("detector")
@click.argument("source")
@click.argument("destination")
def convert_detector_cmd(source, destination):
    """Convert a detector safetensors export to an orbax checkpoint."""
    from .models.convert import convert_detector

    convert_detector(source, destination)
    click.echo(json.dumps({"destination": destination}))


# -- media conversion -------------------------------------------------------

@main.group()
def media():
    """Media conversion (reference images_to_video / video_to_images)."""


@media.command("images-to-video")
@click.argument("pattern")
@click.argument("output")
@click.option("--rate", default=29.97, help="output frame rate")
@click.option("--codec", default="MJPG", help="fourcc codec")
def images_to_video_cmd(pattern, output, rate, codec):
    """Encode images matching PATTERN (glob or '{}' template) into the
    OUTPUT video file, via a real ImageReadFile->VideoWriteFile
    pipeline (reference elements/media/images_to_video.py:1-33)."""
    from .media_convert import images_to_video

    frames = images_to_video(pattern, output, rate=rate, codec=codec)
    click.echo(json.dumps({"frames": frames, "output": output}))


@media.command("video-to-images")
@click.argument("video")
@click.argument("pattern")
def video_to_images_cmd(video, pattern):
    """Decode VIDEO into per-frame images at PATTERN (a '{}' template,
    e.g. out/frame_{}.png), via a real VideoReadFile->ImageWriteFile
    pipeline (reference elements/media/video_to_images.py:1-42)."""
    from .media_convert import video_to_images

    frames = video_to_images(video, pattern)
    click.echo(json.dumps({"frames": frames, "pattern": pattern}))


# -- chaos ------------------------------------------------------------------

@main.command()
@click.option("--pipelines", default=2,
              help="pipeline processes to spawn (>= 2 so adoption has "
                   "a survivor)")
@click.option("--frames", default=12, help="frames the session streams")
@click.option("--mode",
              type=click.Choice(["kill", "rolling", "controller"]),
              default="kill",
              help="kill: SIGKILL one pipeline mid-stream and assert "
                   "adoption + supervised respawn; rolling: "
                   "drain+respawn every pipeline in sequence and "
                   "assert zero drops; controller: overload a pilot "
                   "running the fleet controller until it scales out, "
                   "SIGKILL the spawned peer mid-stream, and assert "
                   "respawn + zero-drop convergence")
@click.option("--hang-ms", default=0.0,
              help="SIGSTOP the victim this long before the kill "
                   "(process_hang, kill mode only)")
@click.option("--busy-ms", default=60.0, help="per-stage busy time")
@click.option("--timeout", default=180.0, help="overall deadline")
def chaos(pipelines, frames, mode, hang_ms, busy_ms, timeout):
    """Multi-process chaos driver (ISSUE 13): native MQTT broker +
    registrar + N pipeline processes sharing a journal directory, a
    standalone gateway in THIS process, and a live WebSocket session
    streaming through the fleet while pipelines die (SIGKILL) or
    drain under it.  Asserts in-order, duplicate-free, zero-drop
    delivery across the failover."""
    from .faults.chaos import run_chaos

    result = run_chaos(pipelines=pipelines, frames=frames, mode=mode,
                       hang_ms=hang_ms, busy_ms=busy_ms,
                       timeout=timeout, echo=click.echo)
    if not result.get("ok"):
        raise click.ClickException(f"chaos walk failed: {result}")
    click.echo("chaos walk passed")


# -- fleetctl (ISSUE 20: guarded elastic fleet controller) ------------------

def _fleetctl_request(name, transport, timeout, command, arguments):
    """Publish one ``(fleetctl <response_topic> <command> ...)`` to
    the named pipeline and return its JSON report (do_request
    pattern)."""
    from .pipeline import PROTOCOL_PIPELINE
    from .services import ServiceFilter, do_request

    runtime = _runtime(transport)
    reports = []

    def request(proxy, response_topic):
        proxy.fleetctl(response_topic, command, *arguments)

    def response(items):
        for reply_command, parameters in items:
            if reply_command == "fleetctl" and parameters:
                try:
                    reports.append(json.loads(str(parameters[0])))
                except ValueError:
                    reports.append({"raw": str(parameters[0])})

    do_request(runtime, None,
               ServiceFilter(name=name, protocol=PROTOCOL_PIPELINE),
               request, response)
    runtime.run(until=lambda: bool(reports), timeout=timeout)
    if not reports:
        click.echo(f"no fleetctl reply from pipeline {name!r} "
                   f"(not found, or not answering?)", err=True)
        sys.exit(1)
    report = reports[0]
    if isinstance(report, dict) and report.get("error"):
        raise click.ClickException(report["error"])
    return report


@main.group()
def fleetctl():
    """Operate a live fleet controller (``controller:`` pipelines):
    inspect its decision surface, pause/resume the loop, or force one
    guarded action."""


@fleetctl.command("status")
@click.argument("name")
@_transport_option
@click.option("--timeout", default=5.0, help="discovery wait seconds")
def fleetctl_status(name, transport, timeout):
    """Show the named pipeline's controller status: mode, fleet size,
    budget left, last decision, supervisor roster."""
    report = _fleetctl_request(name, transport, timeout, "status", ())
    click.echo(json.dumps(report, indent=2, default=str))


@fleetctl.command("pause")
@click.argument("name")
@_transport_option
@click.option("--timeout", default=5.0, help="discovery wait seconds")
def fleetctl_pause(name, transport, timeout):
    """Pause the control loop (the fleet keeps serving as tuned)."""
    report = _fleetctl_request(name, transport, timeout, "pause", ())
    click.echo(f"controller paused "
               f"(fleet_size={report.get('status', {}).get('fleet_size')})")


@fleetctl.command("resume")
@click.argument("name")
@_transport_option
@click.option("--timeout", default=5.0, help="discovery wait seconds")
def fleetctl_resume(name, transport, timeout):
    """Resume a paused control loop."""
    report = _fleetctl_request(name, transport, timeout, "resume", ())
    click.echo(f"controller resumed "
               f"(fleet_size={report.get('status', {}).get('fleet_size')})")


@fleetctl.command("force-action")
@click.argument("name")
@click.argument("kind")
@_transport_option
@click.option("--detail", default=None,
              help='action detail as JSON, e.g. \'{"to": 4}\'')
@click.option("--yes", is_flag=True,
              help="skip the confirmation prompt")
@click.option("--timeout", default=5.0, help="discovery wait seconds")
def fleetctl_force(name, transport, kind, detail, yes, timeout):
    """Force ONE action now (stage_inflight | device_inflight |
    replicas | admit | spawn | retire | swap | rollback), bypassing
    hysteresis and cooldown -- the budget, the fence, and observe
    mode still apply."""
    if detail is not None:
        try:
            json.loads(detail)
        except ValueError as error:
            raise click.BadParameter(f"--detail is not JSON: {error}")
    if not yes:
        click.confirm(f"force {kind!r} on pipeline {name!r} "
                      f"(bypasses hysteresis + cooldown)?", abort=True)
    arguments = (kind,) if detail is None else (kind, detail)
    report = _fleetctl_request(name, transport, timeout, "force",
                               arguments)
    refused = report.get("refused")
    if refused:
        raise click.ClickException(f"refused: {refused}")
    click.echo(f"forced {kind}: done "
               f"(actions={report.get('status', {}).get('actions')})")


@fleetctl.command("swap")
@click.argument("name")
@click.argument("stage")
@click.argument("parameter")
@click.argument("value")
@_transport_option
@click.option("--yes", is_flag=True,
              help="skip the confirmation prompt")
@click.option("--timeout", default=5.0, help="discovery wait seconds")
def fleetctl_swap(name, transport, stage, parameter, value, yes,
                  timeout):
    """Begin a canary-gated replica-by-replica swap of one element
    parameter (the "model version" knob) on STAGE.  VALUE is JSON
    (bare strings pass through).  Burn above the canary ratio rolls
    every swapped replica back automatically."""
    if not yes:
        click.confirm(f"swap {stage}.{parameter}={value!r} on "
                      f"{name!r} replica-by-replica (canary-gated)?",
                      abort=True)
    report = _fleetctl_request(name, transport, timeout, "swap",
                               (stage, parameter, value))
    refused = report.get("refused")
    if refused:
        raise click.ClickException(f"refused: {refused}")
    click.echo(f"swap of {stage}.{parameter} begun "
               f"(watch: fleetctl status {name})")


# -- broker -----------------------------------------------------------------

@main.command()
@click.option("--port", default=1883, help="listen port (0 = assigned)")
def broker(port):
    """Run the in-tree native MQTT broker (mosquitto equivalent)."""
    import time

    from .transport import BrokerProcess

    instance = BrokerProcess(port=port, export_env=False).start()
    click.echo(f"mqtt broker listening on {instance.port}")
    try:
        while instance.process.poll() is None:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        instance.stop()


# -- system lifecycle -------------------------------------------------------
# The reference manages its fabric with shell scripts
# (scripts/system_start.sh / system_stop.sh / system_reset.sh); with the
# broker in-tree this is a CLI: start/stop/status/reset.

def _system_state_path():
    import pathlib
    import tempfile

    base = os.environ.get("AIKO_STATE_DIR") or tempfile.gettempdir()
    return pathlib.Path(base) / "aiko_tpu_system.json"


@main.group()
def system():
    """Start/stop the single-host fabric: native broker + registrar."""


@system.command("start")
@click.option("--port", default=1883, help="broker port (0 = assigned)")
def system_start(port):
    """Launch the native MQTT broker and a registrar as detached
    background processes (reference scripts/system_start.sh)."""
    import subprocess
    import time

    from .transport.broker import broker_binary

    state_path = _system_state_path()
    if state_path.exists():
        raise click.ClickException(
            f"system already started ({state_path}); "
            "run 'system stop' first")
    # Children are detached AND get their own output files: inheriting
    # this CLI's stdout/stderr would keep those pipes open forever for
    # any caller capturing them.
    broker_log = open(state_path.with_suffix(".broker.log"), "w")
    registrar_log = open(state_path.with_suffix(".registrar.log"), "w")
    broker_process = subprocess.Popen(
        [str(broker_binary()), str(port)],
        stdout=subprocess.PIPE, stderr=broker_log, text=True,
        start_new_session=True)
    line = broker_process.stdout.readline().strip()
    if not line.startswith("LISTENING "):
        broker_process.terminate()
        raise click.ClickException(f"broker failed: {line!r}")
    actual_port = int(line.split()[1])
    environment = dict(os.environ)
    environment["AIKO_MQTT_HOST"] = "127.0.0.1"
    environment["AIKO_MQTT_PORT"] = str(actual_port)
    registrar_process = subprocess.Popen(
        [sys.executable, "-m", "aiko_services_tpu", "registrar",
         "-t", "mqtt"], env=environment, start_new_session=True,
        stdout=registrar_log, stderr=registrar_log)
    time.sleep(0.5)                    # catch instant-exit failures
    if registrar_process.poll() is not None:
        broker_process.terminate()
        raise click.ClickException(
            f"registrar exited rc={registrar_process.returncode}; "
            f"see {registrar_log.name}")
    state_path.write_text(json.dumps(
        {"port": actual_port, "broker_pid": broker_process.pid,
         "registrar_pid": registrar_process.pid}))
    click.echo(f"broker :{actual_port} (pid {broker_process.pid}), "
               f"registrar (pid {registrar_process.pid})")


_SYSTEM_PROCESS_MARKS = {"broker_pid": "mqtt_broker",
                         "registrar_pid": "registrar"}


def _system_pid_matches(pid: int, mark: str) -> bool:
    """Identity check before signalling a pidfile PID: a crash + PID
    reuse must not let 'system stop' kill an unrelated process."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as stream:
            return mark.encode() in stream.read()
    except OSError:
        return False


@system.command("stop")
def system_stop():
    """Stop the processes started by 'system start'."""
    import signal as signal_module

    state_path = _system_state_path()
    if not state_path.exists():
        raise click.ClickException("system not started")
    state = json.loads(state_path.read_text())
    for key, mark in _SYSTEM_PROCESS_MARKS.items():
        name = key.split("_")[0]
        if not _system_pid_matches(state[key], mark):
            click.echo(f"{name} already gone (or pid reused)", err=True)
            continue
        try:
            os.kill(state[key], signal_module.SIGTERM)
            click.echo(f"stopped {name} (pid {state[key]})")
        except ProcessLookupError:
            click.echo(f"{name} already gone", err=True)
    state_path.unlink()


@system.command("status")
def system_status():
    """Report fabric liveness."""
    from .utils import mqtt_broker_reachable

    state_path = _system_state_path()
    if not state_path.exists():
        click.echo("system: not started")
        return
    state = json.loads(state_path.read_text())
    up = mqtt_broker_reachable("127.0.0.1", state["port"], timeout=1.0)
    click.echo(f"broker :{state['port']} "
               f"{'up' if up else 'DOWN'} (pid {state['broker_pid']})")
    registrar_up = _system_pid_matches(
        state["registrar_pid"], _SYSTEM_PROCESS_MARKS["registrar_pid"])
    click.echo(f"registrar {'up' if registrar_up else 'DOWN'} "
               f"(pid {state['registrar_pid']})")


@system.command("reset")
@_transport_option
def system_reset(transport):
    """Clear the retained registrar election record (reference
    scripts/system_reset.sh -- needed after a broker kept state across
    an unclean shutdown; live secondaries also self-heal via the
    stale-primary probe)."""
    runtime = _runtime(transport)
    runtime.message.publish(runtime.topic_registrar_boot, "",
                            retain=True)
    runtime.run(until=lambda: False, timeout=0.5)
    click.echo(f"cleared retained {runtime.topic_registrar_boot}")


# -- dashboard --------------------------------------------------------------

@main.command()
@_transport_option
def dashboard(transport):
    """Terminal dashboard: browse services, watch share dicts, tail logs."""
    from .dashboard import run_dashboard

    run_dashboard(transport)


if __name__ == "__main__":
    main()
