"""``zmq://`` DataScheme + Read/Write elements (reference:
src/aiko_services/elements/media/scheme_zmq.py:40-150, text_io.py
TextReadZMQ/TextWriteZMQ, image_io.py ImageReadZMQ/ImageWriteZMQ).

The out-of-band bulk data plane for frames that must cross hosts with no
ICI path (SURVEY.md section 5.8): PUSH/PULL pair over
``zmq://host:port``.  Payloads are either raw bytes/UTF-8 text or
npy-encoded arrays (``pipeline.tensor.encode_array``) tagged by a 1-byte
kind prefix, so jax arrays round-trip typed and shaped.
"""

from __future__ import annotations

import queue
import threading

try:
    import zmq
    _HAVE_ZMQ = True
except ImportError:                                 # pragma: no cover
    _HAVE_ZMQ = False

import jax.numpy as jnp

from ..pipeline import DataScheme, DataSource, DataTarget, StreamEvent
from ..pipeline.stream import Stream
from ..pipeline.tensor import decode_array, encode_array

__all__ = ["DataSchemeZMQ", "TextReadZMQ", "TextWriteZMQ",
           "ImageReadZMQ", "ImageWriteZMQ"]

_KIND_TEXT = b"t"
_KIND_BYTES = b"b"
_KIND_ARRAY = b"a"
_RECV_POLL_MS = 100


def encode_payload(value) -> bytes:
    if isinstance(value, (bytes, bytearray)):
        return _KIND_BYTES + bytes(value)
    if isinstance(value, str):
        return _KIND_TEXT + value.encode()
    if hasattr(value, "shape"):
        return _KIND_ARRAY + encode_array(value)
    return _KIND_TEXT + str(value).encode()


def decode_payload(data: bytes):
    kind, body = data[:1], data[1:]
    if kind == _KIND_TEXT:
        return body.decode()
    if kind == _KIND_ARRAY:
        return jnp.asarray(decode_array(body))
    return body


@DataScheme.register("zmq")
class DataSchemeZMQ(DataScheme):
    """Source: PULL socket bound (or connected) with a background recv
    thread feeding a queue drained by a frame generator; target: PUSH
    socket."""

    def __init__(self, element):
        super().__init__(element)
        self._context = None
        self._socket = None
        self._thread = None
        self._stop = threading.Event()
        self._queue: "queue.Queue[bytes]" = queue.Queue()

    @staticmethod
    def _endpoint(url: str) -> str:
        return "tcp://" + DataScheme.parse_data_url_path(url)

    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        if not _HAVE_ZMQ:
            return StreamEvent.ERROR, {"diagnostic": "pyzmq missing"}
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.PULL)
        endpoint = self._endpoint(data_sources[0])
        bind, _ = self.element.get_parameter("zmq_bind", True)
        if bind:
            self._socket.bind(endpoint)
        else:
            self._socket.connect(endpoint)

        def recv_loop():
            poller = zmq.Poller()
            poller.register(self._socket, zmq.POLLIN)
            while not self._stop.is_set():
                if poller.poll(_RECV_POLL_MS):
                    self._queue.put(self._socket.recv())

        self._thread = threading.Thread(
            target=recv_loop, daemon=True,
            name=f"zmq-recv-{self.element.name}")
        self._thread.start()

        def generator(stream_):
            try:
                data = self._queue.get_nowait()
            except queue.Empty:
                return StreamEvent.NO_FRAME, {}
            return StreamEvent.OKAY, {"payload": decode_payload(data)}

        self.element.create_frames(stream, frame_generator or generator,
                                   rate=rate)
        return StreamEvent.OKAY, {}

    def create_targets(self, stream: Stream, data_targets):
        if not _HAVE_ZMQ:
            return StreamEvent.ERROR, {"diagnostic": "pyzmq missing"}
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.PUSH)
        endpoint = self._endpoint(data_targets[0])
        bind, _ = self.element.get_parameter("zmq_bind", False)
        if bind:
            self._socket.bind(endpoint)
        else:
            self._socket.connect(endpoint)
        return StreamEvent.OKAY, {}

    def send(self, value):
        self._socket.send(encode_payload(value))

    def _close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        if self._socket is not None:
            self._socket.close(linger=0)
            self._socket = None

    def destroy_sources(self, stream: Stream):
        self._close()

    def destroy_targets(self, stream: Stream):
        self._close()


class TextReadZMQ(DataSource):
    """Emits ``text`` received over zmq:// (reference
    text_io.py:202-220)."""

    def process_frame(self, stream, payload=None, **inputs):
        return StreamEvent.OKAY, {"text": str(payload)}


class TextWriteZMQ(DataTarget):
    """Sends ``text`` over zmq:// (reference text_io.py:356-369)."""

    def process_frame(self, stream, text=None, **inputs):
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeZMQ):
            return StreamEvent.ERROR, {
                "diagnostic": "TextWriteZMQ requires zmq:// targets"}
        scheme.send(str(text))
        return StreamEvent.OKAY, {"text": text}


class ImageReadZMQ(DataSource):
    """Emits ``image`` arrays received over zmq:// (reference
    image_io.py:307-343)."""

    def process_frame(self, stream, payload=None, **inputs):
        return StreamEvent.OKAY, {"image": payload}


class ImageWriteZMQ(DataTarget):
    """Sends ``image`` arrays over zmq:// (reference
    image_io.py:407-425)."""

    def process_frame(self, stream, image=None, **inputs):
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeZMQ):
            return StreamEvent.ERROR, {
                "diagnostic": "ImageWriteZMQ requires zmq:// targets"}
        scheme.send(image)
        return StreamEvent.OKAY, {"image": image}
