"""Classical-CV detector elements: faces and ArUco fiducial markers
(reference: src/aiko_services/examples/face/face.py:52 FaceDetector,
examples/aruco_marker/aruco.py:80 ArucoMarkerDetector, :136
ArucoMarkerOverlay).

These are host-side cv2 detectors -- the work is small and pre-neural
(Haar cascade, fiducial decoding), so there is nothing to put on the
TPU; the JAX :class:`~aiko_services_tpu.elements.detect.Detector` is the
accelerated path for learned detection.  Both emit the standard overlay
dict (``{"rectangles": [...], "texts": [...]}``) so the existing
:class:`ImageOverlay` draws them with no extra element -- the reference
needed a separate ArucoMarkerOverlay drawing via cv2 lines; here the
polygon corners are also passed through for consumers that want the
exact quadrilateral.

cv2 is a gated import like the reference: the module loads without it,
elements error per-stream with a diagnostic.
"""

from __future__ import annotations

import numpy as np

from ..pipeline import PipelineElement, StreamEvent
from ..pipeline.stream import Stream

__all__ = ["FaceDetect", "ArucoMarkerDetect"]

try:
    import cv2
    _HAVE_CV2 = True
except ImportError:                                 # pragma: no cover
    _HAVE_CV2 = False


def _to_gray(array: np.ndarray) -> np.ndarray:
    if array.ndim == 2:
        return array
    return cv2.cvtColor(array, cv2.COLOR_RGB2GRAY)


from .image import as_uint8 as _as_uint8


class _CascadeBackend:
    """Haar cascade (cv2 4.x; removed in the cv2 5 objdetect split)."""

    def __init__(self, element):
        path, found = element.get_parameter("cascade")
        if not found:
            path = (cv2.data.haarcascades
                    + "haarcascade_frontalface_default.xml")
        self._cascade = cv2.CascadeClassifier(path)
        if self._cascade.empty():
            raise RuntimeError(f"cannot load face cascade {path}")
        scale, _ = element.get_parameter("scale_factor", 1.1)
        neighbors, _ = element.get_parameter("min_neighbors", 5)
        min_size, _ = element.get_parameter("min_size", 24)
        self._kwargs = {"scaleFactor": float(scale),
                        "minNeighbors": int(neighbors),
                        "minSize": (int(min_size), int(min_size))}

    def detect(self, array: np.ndarray) -> np.ndarray:
        boxes = self._cascade.detectMultiScale(_to_gray(array),
                                               **self._kwargs)
        return np.asarray(boxes).reshape(-1, 4)


class _YuNetBackend:
    """cv2.FaceDetectorYN -- the cv2 5.x face path; needs an ONNX model
    file supplied via the ``model`` element parameter."""

    def __init__(self, element):
        model, found = element.get_parameter("model")
        if not found:
            raise RuntimeError(
                "this cv2 build has no CascadeClassifier; supply a "
                "YuNet ONNX file via the 'model' parameter")
        threshold, _ = element.get_parameter("score_threshold", 0.8)
        self._detector = cv2.FaceDetectorYN_create(
            str(model), "", (0, 0), float(threshold))

    def detect(self, array: np.ndarray) -> np.ndarray:
        if array.ndim == 2:
            array = cv2.cvtColor(array, cv2.COLOR_GRAY2BGR)
        height, width = array.shape[:2]
        self._detector.setInputSize((width, height))
        _, faces = self._detector.detect(array)
        if faces is None:
            return np.zeros((0, 4))
        return np.asarray(faces)[:, :4]             # x y w h (+landmarks)


def _default_face_backend(element):
    if not _HAVE_CV2:
        raise RuntimeError("cv2 missing")
    if hasattr(cv2, "CascadeClassifier"):
        return _CascadeBackend(element)
    return _YuNetBackend(element)


# Injectable: callable(element) -> object with detect(ndarray) -> [N, 4].
face_backend_factory = _default_face_backend


class FaceDetect(PipelineElement):
    """``image`` -> ``overlay`` rectangles around detected faces +
    ``faces`` list (reference face.py:52, which runs deepface/retinaface;
    here a pluggable cv2 backend -- Haar cascade where the build has it,
    YuNet via the ``model`` parameter on cv2 5.x -- same output
    contract).

    Parameters: ``scale_factor`` (default 1.1), ``min_neighbors`` (5),
    ``min_size`` (24), ``cascade``/``model`` (backend files).
    Cumulative detection count is shared as ``{element}.detections``
    (reference ``self.share["detections"]``)."""

    host_inputs = ("image",)    # cv2 runs on host: one counted fetch

    def __init__(self, context):
        super().__init__(context)
        self._backend = None
        self._detections = 0

    def process_frame(self, stream: Stream, image=None, **inputs):
        try:
            if self._backend is None:
                self._backend = face_backend_factory(self)
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"face backend unavailable: {error}"}
        array = _as_uint8(image)
        try:
            boxes = self._backend.detect(array)
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"face detection failed: {error}"}
        height, width = array.shape[:2]
        rectangles, faces = [], []
        for (x, y, w, h) in np.asarray(boxes).reshape(-1, 4):
            rectangles.append({"x": x / width, "y": y / height,
                               "w": w / width, "h": h / height,
                               "name": "face"})
            faces.append({"x": int(x), "y": int(y),
                          "w": int(w), "h": int(h)})
        self._detections += len(faces)
        producer = getattr(self.pipeline, "ec_producer", None)
        if producer is not None:
            producer.update(f"{self.name}.detections", self._detections)
        return StreamEvent.OKAY, {
            "image": image,
            "overlay": {"rectangles": rectangles},
            "faces": faces}


class ArucoMarkerDetect(PipelineElement):
    """``image`` -> ``markers`` (id + corner quadrilateral) + standard
    ``overlay`` (bounding rectangle labelled ``aruco <id>`` per marker)
    (reference aruco.py:80-136).

    Parameter ``aruco_tags`` selects the dictionary by its cv2 name
    (default ``DICT_4X4_50``, the reference default)."""

    host_inputs = ("image",)    # cv2 runs on host: one counted fetch

    def __init__(self, context):
        super().__init__(context)
        self._detector = None
        self._tags = None

    def _marker_detector(self):
        tags, _ = self.get_parameter("aruco_tags", "DICT_4X4_50")
        if self._detector is None or tags != self._tags:
            table = getattr(cv2.aruco, str(tags), None)
            if table is None:
                raise RuntimeError(f"unknown ArUco dictionary {tags!r}")
            dictionary = cv2.aruco.getPredefinedDictionary(table)
            self._detector = cv2.aruco.ArucoDetector(
                dictionary, cv2.aruco.DetectorParameters())
            self._tags = tags
        return self._detector

    def process_frame(self, stream: Stream, image=None, **inputs):
        if not _HAVE_CV2:
            return StreamEvent.ERROR, {"diagnostic": "cv2 missing"}
        array = _as_uint8(image)
        try:
            corners, ids, _rejected = \
                self._marker_detector().detectMarkers(_to_gray(array))
        except (cv2.error, RuntimeError) as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"aruco detection failed: {error}"}
        height, width = array.shape[:2]
        markers, rectangles = [], []
        if ids is not None:
            for quad, marker_id in zip(corners, np.asarray(ids).flatten()):
                points = np.asarray(quad).reshape(4, 2)
                markers.append({"id": int(marker_id),
                                "corners": points.tolist()})
                x1, y1 = points.min(axis=0)
                x2, y2 = points.max(axis=0)
                rectangles.append({
                    "x": float(x1) / width, "y": float(y1) / height,
                    "w": float(x2 - x1) / width,
                    "h": float(y2 - y1) / height,
                    "name": f"aruco {int(marker_id)}"})
        return StreamEvent.OKAY, {
            "image": image,
            "overlay": {"rectangles": rectangles},
            "markers": markers}
