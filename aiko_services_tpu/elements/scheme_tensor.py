"""``tensor://`` DataScheme + Read/Write elements over the native
tensor_pipe transport (native/tensor_pipe.cpp; reference equivalent:
the libzmq-backed ``zmq://`` scheme, elements/media/scheme_zmq.py:40 --
this one is the framework's own C++, zero external dependencies).

``tensor://host:port`` targets connect-and-send; sources listen on the
port and pump received arrays as frames.  Arrays cross typed and
shaped (raw bytes + JSON header), so a downstream element sees the
same jax array the upstream one emitted, modulo the host hop.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..pipeline import DataScheme, DataSource, DataTarget, StreamEvent
from ..pipeline.stream import Stream
from ..transport.tensor_pipe import TensorPipeClient, TensorPipeServer

__all__ = ["DataSchemeTensorPipe", "TensorReadPipe", "TensorWritePipe"]

_RECV_POLL_S = 0.1


def _host_port(url: str) -> tuple:
    """``tensor://host:port`` -> (host, port); raises ValueError with a
    usable message on a missing/malformed port (callers surface it as
    a StreamEvent.ERROR diagnostic)."""
    location = DataScheme.parse_data_url_path(url)
    host, separator, port = location.rpartition(":")
    if not separator or not port.isdigit():
        raise ValueError(f"{url!r}: expected tensor://host:port")
    return host or "127.0.0.1", int(port)


@DataScheme.register("tensor")
class DataSchemeTensorPipe(DataScheme):
    """Sources bind a TensorPipeServer; targets hold a client."""

    def __init__(self, element):
        super().__init__(element)
        self._server = None
        self._client = None

    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        if len(data_sources) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"tensor:// takes one URL, got "
                              f"{len(data_sources)}"}
        try:
            host, port = _host_port(data_sources[0])
            self._server = TensorPipeServer(host, port)
        except (ValueError, OSError) as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"tensor listen failed: {error}"}
        stream.variables["tensor_pipe_port"] = self._server.port

        def generator(stream_):
            frame = self._server.recv(timeout=_RECV_POLL_S)
            if frame is None:
                return StreamEvent.NO_FRAME, {}
            name, array = frame
            return StreamEvent.OKAY, {
                "tensor": jnp.asarray(array), "name": name}

        self.element.create_frames(stream,
                                   frame_generator or generator,
                                   rate=rate)
        return StreamEvent.OKAY, {}

    def create_targets(self, stream: Stream, data_targets):
        if len(data_targets) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"tensor:// takes one URL, got "
                              f"{len(data_targets)}"}
        try:
            host, port = _host_port(data_targets[0])
            self._client = TensorPipeClient(host, port)
        except (ValueError, ConnectionError) as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"tensor connect failed: {error}"}
        return StreamEvent.OKAY, {}

    def send(self, value, name: str = ""):
        self._client.send(value, name=name)

    def destroy_sources(self, stream: Stream):
        if self._server is not None:
            self._server.close()
            self._server = None

    def destroy_targets(self, stream: Stream):
        if self._client is not None:
            self._client.close()
            self._client = None


class TensorReadPipe(DataSource):
    """``data_sources: tensor://host:port`` -> ``tensor`` frames (the
    receiving end of a cross-host pipeline hop).  The generator puts
    ``tensor``/``name`` into the swag; the inherited pass-through
    process_frame leaves them untouched (re-emitting named keys here
    would clobber the swag with this element's own -- undeclared --
    inputs)."""


class TensorWritePipe(DataTarget):
    """``tensor`` frames -> ``data_targets: tensor://host:port``;
    passes the tensor through for further local stages.  Parameter
    ``input_name`` selects a differently-named swag value."""

    def process_frame(self, stream, tensor=None, **inputs):
        scheme = self.scheme_for(stream)
        if scheme is None:
            return StreamEvent.ERROR, {
                "diagnostic": "tensor target not initialized"}
        input_name, _ = self.get_parameter("input_name", "tensor")
        value = tensor if input_name == "tensor" \
            else inputs.get(input_name)
        if value is None:
            return StreamEvent.ERROR, {
                "diagnostic": f"no {input_name!r} input on frame"}
        try:
            scheme.send(value, name=str(stream.stream_id))
        except ConnectionError as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"tensor send failed: {error}"}
        return StreamEvent.OKAY, {"tensor": value, **inputs}
