"""Live audio endpoints: microphone capture and speaker playback
(reference: src/aiko_services/elements/media/audio_io.py:412
PE_MicrophonePA, :466 PE_MicrophoneSD, :540 PE_Speaker).

``mic://<device>`` sources and ``speaker://<device>`` targets.  Capture
runs on the audio backend's own thread into a bounded queue; the frame
generator drains it on the source pump thread (the webcam pattern,
video.py:134-168) -- NO_FRAME while the queue is empty, so an idle
microphone never busy-spins the pipeline.

The hardware backend is ``sounddevice`` when importable; it is not in
this image, so the backends are injectable module hooks
(:data:`input_backend_factory` / :data:`output_backend_factory`) --
tests drive the elements with fake backends, and a deployment with
working audio gets sounddevice automatically.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

import jax.numpy as jnp

from ..pipeline import DataScheme, DataSource, DataTarget, StreamEvent
from ..pipeline.stream import Stream

__all__ = ["MicrophoneRead", "SpeakerWrite", "DataSchemeMic",
           "DataSchemeSpeaker", "input_backend_factory",
           "output_backend_factory"]


class SounddeviceInput:
    """Microphone blocks via sounddevice.InputStream -> bounded queue."""

    def __init__(self, device, sample_rate: int, block_samples: int,
                 channels: int = 1, queue_depth: int = 32):
        import sounddevice  # gated: not in every image

        self._queue: queue.Queue = queue.Queue(maxsize=queue_depth)

        def callback(indata, frames, time_info, status):
            try:
                self._queue.put_nowait(np.array(indata, dtype=np.float32))
            except queue.Full:
                pass                    # drop: live capture never blocks

        self._stream = sounddevice.InputStream(
            device=device or None, samplerate=sample_rate,
            blocksize=block_samples, channels=channels, dtype="float32",
            callback=callback)
        self._stream.start()

    def read(self, timeout: float = 0.0):
        """One captured block [block, C] or None if none pending."""
        try:
            return self._queue.get(timeout=timeout) if timeout \
                else self._queue.get_nowait()
        except queue.Empty:
            return None

    def close(self):
        self._stream.stop()
        self._stream.close()


class SounddeviceOutput:
    """Speaker playback via sounddevice.OutputStream."""

    def __init__(self, device, sample_rate: int, channels: int = 1):
        import sounddevice

        self._stream = sounddevice.OutputStream(
            device=device or None, samplerate=sample_rate,
            channels=channels, dtype="float32")
        self._stream.start()

    def write(self, samples: np.ndarray):
        self._stream.write(np.ascontiguousarray(samples,
                                                dtype=np.float32))

    def close(self):
        self._stream.stop()
        self._stream.close()


# Injectable for tests / alternative audio stacks: callables with the
# SounddeviceInput / SounddeviceOutput constructor signatures.
input_backend_factory = SounddeviceInput
output_backend_factory = SounddeviceOutput


def _speaker_key(element_name: str) -> str:
    # Single definition shared by DataSchemeSpeaker and SpeakerWrite.
    return f"{element_name}.speaker_backend"


def _device_id(path: str):
    """``mic://1`` means PortAudio device *index* 1: sounddevice treats a
    str as a name-substring match, so digit-only paths must become ints."""
    return int(path) if path.isdigit() else path


class _PlaybackPump:
    """Writer thread between the engine and a (blocking) output backend.

    ``OutputStream.write`` blocks for the real-time length of the samples;
    running it on the single-threaded engine would stall every stream in
    the process for the playback duration.  The pump mirrors the capture
    pattern: the engine enqueues, a daemon thread drains."""

    def __init__(self, backend, queue_depth: int = 64,
                 label: str = "speaker"):
        self.backend = backend      # public: callers may force-kill a
        self._label = label         # wedged backend after close()
        self._queue: queue.Queue = queue.Queue(maxsize=queue_depth)
        self._error: Exception | None = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"aiko.{label}.pump")
        self._thread.start()

    def _run(self):
        while True:
            samples = self._queue.get()
            if samples is None:
                break
            try:
                self.backend.write(samples)
            except Exception as error:
                self._error = error
        self.backend.close()        # sole closer: never races a write()

    def write(self, samples: np.ndarray, timeout: float = 1.0):
        self._raise_backend_error()
        try:
            self._queue.put(samples, timeout=timeout)
        except queue.Full:
            raise RuntimeError(
                f"{self._label} backlog exceeded (producer faster than "
                "the backend drains; sample/drop upstream or raise "
                "queue_depth)") from None

    def try_write(self, item) -> bool:
        """Drop-on-full enqueue (video semantics: a slow encoder drops
        frames rather than stalling or erroring the stream).  Returns
        False when the frame was dropped; raises only for backend
        failures."""
        self._raise_backend_error()
        try:
            self._queue.put_nowait(item)
            return True
        except queue.Full:
            return False

    def _raise_backend_error(self):
        if self._error is not None:
            error, self._error = self._error, None
            raise RuntimeError(
                f"{self._label} backend failed: {error}")

    def close(self):
        """Signal the pump to finish and close the backend.  The backend
        close always happens on the pump thread -- sounddevice/PortAudio
        stream ops are not safe concurrently with an in-flight write --
        so a stalled write can at worst leak the daemon thread, never
        crash native code.  Bounded wait for the normal drain case;
        returns False when the thread is still wedged in a write (the
        caller may then force-kill ``self.backend`` if the backend
        supports it -- see the rtsp target scheme)."""
        try:
            self._queue.put_nowait(None)
        except queue.Full:          # drop queued audio on shutdown
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._queue.put(None)
        self._thread.join(timeout=2.0)
        return not self._thread.is_alive()


@DataScheme.register("mic")
class DataSchemeMic(DataScheme):
    """``mic://<device>`` -- opens a live capture backend and pumps its
    blocks as frames."""

    @property
    def _key(self) -> str:
        # Per-element key: two mics in one stream keep distinct handles.
        return f"{self.element.name}.mic_backend"

    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        if len(data_sources) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"mic:// takes exactly one device per "
                              f"element, got {len(data_sources)}"}
        device = _device_id(DataScheme.parse_data_url_path(data_sources[0]))
        sample_rate, _ = self.element.get_parameter("sample_rate", 16000)
        block, _ = self.element.get_parameter("block_samples", 1600)
        channels, _ = self.element.get_parameter("channels", 1)
        try:
            backend = input_backend_factory(
                device, int(sample_rate), int(block), int(channels))
        except Exception as error:       # backend/library/device absent
            return StreamEvent.ERROR, {
                "diagnostic": f"microphone open failed: {error}"}
        stream.variables[self._key] = backend
        stream.variables[f"{self._key}.rate"] = int(sample_rate)
        generator = frame_generator or self._block_generator
        self.element.create_frames(stream, generator, rate=rate)
        return StreamEvent.OKAY, {}

    def _block_generator(self, stream: Stream):
        backend = stream.variables.get(self._key)
        if backend is None:
            return StreamEvent.STOP, {}
        block = backend.read(timeout=0.05)
        if block is None:
            return StreamEvent.NO_FRAME, {}
        return StreamEvent.OKAY, {
            "audio": jnp.asarray(block),
            "sample_rate": stream.variables[f"{self._key}.rate"]}

    def destroy_sources(self, stream: Stream):
        backend = stream.variables.pop(self._key, None)
        if backend is not None:
            backend.close()


@DataScheme.register("speaker")
class DataSchemeSpeaker(DataScheme):
    """``speaker://<device>`` -- opens a playback backend."""

    @property
    def _key(self) -> str:
        return _speaker_key(self.element.name)

    def create_targets(self, stream: Stream, data_targets):
        if len(data_targets) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"speaker:// takes exactly one device per "
                              f"element, got {len(data_targets)}"}
        device = _device_id(DataScheme.parse_data_url_path(data_targets[0]))
        sample_rate, _ = self.element.get_parameter("sample_rate", 16000)
        channels, _ = self.element.get_parameter("channels", 1)
        queue_depth, _ = self.element.get_parameter("queue_depth", 64)
        try:
            backend = output_backend_factory(
                device, int(sample_rate), int(channels))
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"speaker open failed: {error}"}
        stream.variables[self._key] = _PlaybackPump(
            backend, queue_depth=int(queue_depth))
        stream.variables[f"{self._key}.rate"] = int(sample_rate)
        return StreamEvent.OKAY, {}

    def destroy_targets(self, stream: Stream):
        backend = stream.variables.pop(self._key, None)
        if backend is not None:
            backend.close()


class MicrophoneRead(DataSource):
    """Live microphone DataSource: ``data_sources: mic://<device>``;
    emits ``audio`` [block, C] + ``sample_rate`` per captured block
    (reference PE_MicrophoneSD, audio_io.py:466-540)."""


class SpeakerWrite(DataTarget):
    """Live speaker DataTarget: ``data_targets: speaker://<device>``;
    plays each frame's ``audio`` (reference PE_Speaker,
    audio_io.py:540-564)."""

    host_inputs = ("audio",)    # sink: the engine fetches explicitly

    def process_frame(self, stream: Stream, audio=None, sample_rate=None,
                      **inputs):
        key = _speaker_key(self.name)
        backend = stream.variables.get(key)
        if backend is None:
            return StreamEvent.ERROR, {"diagnostic": "speaker not open"}
        device_rate = stream.variables.get(f"{key}.rate")
        if sample_rate is not None and int(sample_rate) != device_rate:
            return StreamEvent.ERROR, {
                "diagnostic": f"speaker opened at {device_rate} Hz but "
                              f"frame audio is {sample_rate} Hz (add "
                              f"AudioResampler)"}
        if audio is not None:
            samples = np.asarray(audio, dtype=np.float32)
            if samples.ndim == 1:
                samples = samples[:, None]
            try:
                backend.write(samples)
            except Exception as error:
                return StreamEvent.ERROR, {
                    "diagnostic": f"speaker write failed: {error}"}
        return StreamEvent.OKAY, {}
