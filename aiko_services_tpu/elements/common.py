"""Trivial/test elements (reference: src/aiko_services/elements/media/
elements.py:19-37 Mock/NoOp, and tests/unit/common.py:14-21 Terminate)."""

from __future__ import annotations

import time

from ..pipeline import PipelineElement, StreamEvent
from ..pipeline.tensor import TPUElement

__all__ = ["Mock", "NoOp", "Identity", "Increment", "Terminate",
           "StageWork"]


class Mock(PipelineElement):
    """Passes inputs straight through as outputs."""

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, dict(inputs)


class NoOp(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, {}


class Identity(Mock):
    pass


class Increment(PipelineElement):
    """x -> x + 1 (the multitude benchmark's per-stage work)."""

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": int(x) + 1}


class Terminate(PipelineElement):
    """Ends the hosting process's event loop -- lets offline tests drive
    the genuine runtime and stop from inside the graph."""

    def process_frame(self, stream, **inputs):
        self.pipeline.runtime.engine.terminate()
        return StreamEvent.OKAY, {}


class StageWork(TPUElement):
    """Synthetic placed-stage workload (stage-pipelining benches,
    dryruns, tests): a jitted multiply on the element's (placed) mesh
    plus a host-blocking wait (``busy_ms``) standing in for a stage
    whose wall time is dominated by waiting on its chips.  Synchronous
    by design -- exactly the shape that serializes the classic
    stage-by-stage walk and that per-stage workers
    (pipeline/stages.py) overlap."""

    def __init__(self, context):
        super().__init__(context)
        self._scale = self.jit(lambda x, f: x * f)

    def process_frame(self, stream, x):
        factor, _ = self.get_parameter("factor", 1.0)
        busy_ms, _ = self.get_parameter("busy_ms", 0.0)
        # The engine's stage hop already resharded x onto this stage's
        # submesh; the jitted compute follows the input's placement.
        y = self._scale(x, float(factor))
        if busy_ms:
            time.sleep(float(busy_ms) / 1000.0)
        return StreamEvent.OKAY, {"x": y}
