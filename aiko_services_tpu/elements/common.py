"""Trivial/test elements (reference: src/aiko_services/elements/media/
elements.py:19-37 Mock/NoOp, and tests/unit/common.py:14-21 Terminate)."""

from __future__ import annotations

from ..pipeline import PipelineElement, StreamEvent

__all__ = ["Mock", "NoOp", "Identity", "Increment", "Terminate"]


class Mock(PipelineElement):
    """Passes inputs straight through as outputs."""

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, dict(inputs)


class NoOp(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, {}


class Identity(Mock):
    pass


class Increment(PipelineElement):
    """x -> x + 1 (the multitude benchmark's per-stage work)."""

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": int(x) + 1}


class Terminate(PipelineElement):
    """Ends the hosting process's event loop -- lets offline tests drive
    the genuine runtime and stop from inside the graph."""

    def process_frame(self, stream, **inputs):
        self.pipeline.runtime.engine.terminate()
        return StreamEvent.OKAY, {}
