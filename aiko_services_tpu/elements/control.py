"""Control-flow elements (reference: src/aiko_services/elements/control/
elements.py:20-57)."""

from __future__ import annotations

from ..pipeline import PipelineElementLoop, StreamEvent
from .expression import evaluate_expression

__all__ = ["Loop"]


class Loop(PipelineElementLoop):
    """Re-runs the graph from ``loop_start`` while the ``condition``
    expression holds (evaluated over bare swag names).  Returns OKAY to
    loop again, LOOP_END to fall through."""

    def process_frame(self, stream, **inputs):
        condition, found = self.get_parameter("condition")
        if not found:
            return StreamEvent.LOOP_END, {}
        frame = stream.frames.get(max(stream.frames)) \
            if stream.frames else None
        swag = {k: v for k, v in (frame.swag if frame else inputs).items()
                if "." not in k}
        limit, _ = self.get_parameter("max_iterations", 1000)
        count_key = f"{self.name}.iterations"
        count = stream.variables.get(count_key, 0) + 1
        stream.variables[count_key] = count
        if count >= int(limit):
            return StreamEvent.LOOP_END, {}
        try:
            keep_looping = bool(evaluate_expression(condition, swag))
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"condition {condition!r}: {error}"}
        return (StreamEvent.OKAY if keep_looping
                else StreamEvent.LOOP_END), {}
