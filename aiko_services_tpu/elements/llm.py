"""LLM serving: actor + pipeline element (BASELINE config 3; reference
equivalent: examples/llm/elements.py:92-212, which forwards chat turns to
an external Ollama/CUDA server via LangChain).

Here serving is native to the framework:

- :class:`LLMService` is an Actor owning a :class:`ContinuousBatcher`
  (models/batching.py): weights and the batched KV cache live in HBM;
  any number of remote callers stream generations concurrently.  Wire
  protocol on ``topic/in``::

      (generate response_topic request_id prompt max_new_tokens temp)

  replies on ``response_topic``::

      (token request_id fragment)     per decode step
      (complete request_id full_text)

  The decode loop rides the event engine: while work is pending the
  service re-posts its pump, so decode ticks interleave with message
  handling instead of blocking the process (the "batching mailbox
  between the actor layer and the device loop" flagged in SURVEY §7).

- :class:`LLM` is a PipelineElement producing ``text`` out of ``text``
  frames, hosting its own model in-process.  To share one model (one
  set of HBM weights) across many pipelines, wrap this element in a
  small pipeline and reference it from the others as a remote stage
  (``deploy: remote``) -- the framework's pause/resume continuation
  carries the frame across, exactly like any other remote element.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

import jax

from ..models import llama
from ..models.batching import ContinuousBatcher, Request
from ..models.checkpoint import maybe_restore as _restore
from ..models.tokenizer import ByteTokenizer, load_tokenizer
from ..pipeline import PipelineElement, StreamEvent
from ..services import Actor
from ..utils import generate, get_logger, parse_bool, parse_number

__all__ = ["LLMService", "LLM", "DetectionCaption", "PROTOCOL_LLM"]

_logger = get_logger("aiko.llm")

PROTOCOL_LLM = "llm:0"


def _collector(tokenizer, collected: list):
    """Emit callback appending non-EOS tokens to ``collected``."""
    eos = set(tokenizer.eos_tokens)

    def emit(request_id, token, finished):
        if token not in eos:
            collected.append(token)
    return emit


class LLMService(Actor):
    """Continuous-batching generation server."""

    def __init__(self, name: str = "llm", runtime=None,
                 config: llama.LlamaConfig | None = None,
                 params=None, tokenizer=None, max_slots: int = 8,
                 checkpoint: str | None = None, seed: int = 0,
                 decode_block: int = 1, inflight: int = 2):
        super().__init__(name, PROTOCOL_LLM, tags=["ec=true"],
                         runtime=runtime)
        if config is None:
            config = llama.LlamaConfig.tiny()
        if params is None:
            params = _restore(
                llama.init_params(jax.random.PRNGKey(seed), config),
                checkpoint)
        self.tokenizer = tokenizer or ByteTokenizer()
        # decode_block > 1 with inflight > 1 is the pipelined serving
        # path (fused multi-step blocks chained device-side) -- the same
        # configuration the bench runs; the wire-facing server defaults
        # stay at one-step dispatches so token streaming is per-step.
        self.batcher = ContinuousBatcher(params, config,
                                         max_slots=max_slots,
                                         decode_block=decode_block,
                                         inflight=inflight)
        # Keyed by (response_topic, request_id): two callers independently
        # choosing the same request_id (both starting at "1") must not
        # collide -- the response topic is the caller's identity.
        self._texts: dict[tuple[str, str], list[int]] = {}
        self._pumping = False
        self.share.update({"model_layers": config.n_layers,
                           "max_slots": max_slots,
                           "active": 0, "queued": 0,
                           "tokens_emitted": 0})

    # -- wire API ----------------------------------------------------------

    def generate(self, response_topic, request_id, prompt,
                 max_new_tokens="128", temperature="0"):
        """(generate response_topic request_id prompt max tokens temp)"""
        key = (str(response_topic), str(request_id))
        self._texts[key] = []
        self.batcher.submit(Request(
            request_id="\x00".join(key),
            prompt_tokens=self.tokenizer.encode(str(prompt)),
            max_new_tokens=int(parse_number(max_new_tokens, 128)),
            temperature=float(parse_number(temperature, 0.0)),
            eos_tokens=self.tokenizer.eos_tokens,
            emit=self._on_token))
        self._start_pump()

    # -- decode pump -------------------------------------------------------

    def _start_pump(self):
        if not self._pumping:
            self._pumping = True
            self.runtime.engine.post_deferred(self._pump)

    def _pump(self):
        active = self.batcher.step()
        self.ec_producer.update("active", self.batcher.active_count)
        self.ec_producer.update("queued", self.batcher.queue_depth)
        self.ec_producer.update("tokens_emitted",
                                self.batcher.tokens_emitted)
        if active or self.batcher.queue_depth \
                or self.batcher.blocks_in_flight:
            # Deferred, not synchronous: new (generate ...) messages
            # interleave between decode ticks and join the batch.
            self.runtime.engine.post_deferred(self._pump)
        else:
            self._pumping = False

    def _on_token(self, batcher_id: str, token: int, finished: bool):
        reply_topic, _, request_id = batcher_id.partition("\x00")
        key = (reply_topic, request_id)
        tokens = self._texts.setdefault(key, [])
        if token not in self.tokenizer.eos_tokens:
            tokens.append(token)
            fragment = self.tokenizer.decode([token])
            self.runtime.message.publish(
                reply_topic,
                generate("token", [request_id, fragment]))
        if finished:
            text = self.tokenizer.decode(tokens)
            self.runtime.message.publish(
                reply_topic, generate("complete", [request_id, text]))
            self._texts.pop(key, None)

    # -- local API ---------------------------------------------------------

    def generate_local(self, prompt: str, max_new_tokens: int = 128,
                       temperature: float = 0.0) -> str:
        """Synchronous generation (drains the batcher inline): for
        single-process callers and tests."""
        collected: list[int] = []
        self.batcher.submit(Request(
            request_id="local",
            prompt_tokens=self.tokenizer.encode(prompt),
            max_new_tokens=max_new_tokens, temperature=temperature,
            eos_tokens=self.tokenizer.eos_tokens,
            emit=_collector(self.tokenizer, collected)))
        self.batcher.run_until_drained()
        return self.tokenizer.decode(collected)


class DetectionCaption(PipelineElement):
    """``detections`` (Detector output dicts) -> ``text`` prompt for a
    downstream LLM stage -- the detect->describe bridge of the
    video->detect->caption pipeline (BASELINE config 4; reference
    equivalent: examples/llm/elements.py:204 Detection, which formats
    detections into the Ollama prompt).

    Parameter ``template`` wraps the summary (``{detections}``
    placeholder)."""

    def process_frame(self, stream, detections=None, **inputs):
        detections = detections or []
        counts: dict[str, int] = {}
        for detection in detections:
            name = str(detection.get("class", "object"))
            counts[name] = counts.get(name, 0) + 1
        summary = ", ".join(
            f"{count} {name}" if count > 1 else name
            for name, count in sorted(counts.items())) or "nothing"
        template, _ = self.get_parameter(
            "template", "Describe a scene containing: {detections}.")
        # Plain replace, not str.format: templates may legitimately
        # contain literal braces (JSON-shaped prompts).
        return StreamEvent.OKAY, {
            "text": str(template).replace("{detections}", summary)}


class LLM(PipelineElement):
    """``text`` -> generated ``text``.

    Parameters: ``max_new_tokens``, ``temperature``, ``system_prompt``,
    ``tokenizer`` (HF directory), ``checkpoint`` (orbax dir),
    ``vocab_size``/``max_seq``/``seed`` (local tiny config),
    ``attention`` (``dense`` | ``flash`` -- the Pallas long-context
    prefill path, 2.5x dense at 8k context), ``quantize`` (weight-only
    int8: halves decode's HBM stream), ``decode_block`` (fuse N decode
    steps per device dispatch: amortizes host round trips), ``inflight``
    (keep N fused/loop blocks in flight, chained device-side: hides the
    dispatch round trip behind device compute), ``max_slots`` (device
    batch width: size to the expected concurrent-frame count; decode is
    weight-HBM-bound at short context, so wider batches decode more
    frames' requests per block at nearly the same step time).

    Device-resident serving (ISSUE 8): ``decode_block_tokens`` > 0
    moves generation into ``llama.decode_loop`` -- on-device sampling,
    per-slot stop detection and an emitted-token ring, ONE counted
    ledger fetch per block (the batcher's ``fetch`` is wired to the
    pipeline TransferLedger, and the worker runs decode ticks under
    the ledger's transfer guard, so a stray per-token host sync FAILS
    under ``transfer_guard: disallow`` instead of silently capping
    tok/s).  ``speculative: off|ngram|draft`` layers multi-token
    decoding onto the loop (``spec_tokens`` drafts per step);
    ``kv_page_tokens`` > 0 switches the KV cache to fixed-size pages
    with a per-slot page table (``kv_pages`` caps the physical pool).
    A device loss mid-generation (or a chaos ``decode_block`` fault)
    replays every live request from its last emitted block: the
    batcher re-prefills prompt + committed tokens and generation
    continues -- nothing already streamed is re-emitted.

    ASYNC by default: each frame parks and its request hops to the
    element's device WORKER THREAD, which owns the model and the shared
    :class:`ContinuousBatcher` -- model build (minutes of jit compiles
    for a 1B model through a congested link), admission, the decode
    loop, and the retire fetches all run OFF the event loop, so they
    never block other stages' frames (detect of frame k+1 proceeds
    while the LLM compiles or decodes).  Requests from many in-flight
    frames/streams decode together in one device batch (continuous
    batching across frames, not per-frame drains); completions post
    back through the engine's thread-safe continuation.  Set parameter
    ``synchronous: true`` for the blocking per-frame path.
    """

    is_async = True

    def __init__(self, context):
        super().__init__(context)
        self._batcher: ContinuousBatcher | None = None
        self._tokenizer = None
        self._request_seq = 0
        # request_id -> complete for parked async frames, so a failing
        # worker can error them out instead of leaving them parked.
        # Owned by the WORKER thread (cancels arrive via the queue).
        self._completes: dict = {}
        # ("request", stream_id, text, complete, request_params,
        # model_params) | ("cancel", prefix); created lazily with the
        # daemon worker thread.
        self._work: queue.Queue | None = None
        # Serializes device access between the worker and the blocking
        # process_frame path (a per-stream ``synchronous: true`` can
        # run while another stream uses the async worker).
        self._device_lock = threading.RLock()
        # Device-loss recovery bookkeeping: consecutive failed decode
        # ticks before the worker gives up replaying (reset by any
        # successful tick), and the telemetry counters' published
        # watermarks (deltas feed the registry).
        self._recover_streak = 0
        self._published_accepted = 0
        self._published_drafted = 0
        self._published_prefix_hits = 0
        self._published_prefix_lookups = 0

    # Model-config parameters, resolved ON THE EVENT LOOP (stream
    # parameter precedence reads the pipeline's current-stream context,
    # which only the loop thread maintains) and shipped to the worker.
    _MODEL_PARAMS = ("checkpoint", "tokenizer", "vocab_size", "max_seq",
                     "seed", "attention", "model", "quantize",
                     "decode_block", "inflight", "max_slots",
                     "decode_block_tokens", "speculative", "spec_tokens",
                     "spec_window", "kv_page_tokens", "kv_pages",
                     "decode_kernel", "sample_top_k", "prefix_cache",
                     "prefix_min_tokens", "spec_autoprobe")

    def _resolve_model_params(self) -> dict:
        resolved = {}
        for name in self._MODEL_PARAMS:
            value, found = self.get_parameter(name, None)
            if found and value is not None:
                resolved[name] = value
        return resolved

    def _resolve_request_params(self) -> dict:
        max_new, _ = self.get_parameter("max_new_tokens", 32)
        temperature, _ = self.get_parameter("temperature", 0.0)
        system_prompt, _ = self.get_parameter("system_prompt", "")
        return {"max_new_tokens": int(max_new),
                "temperature": float(temperature),
                "system_prompt": str(system_prompt or "")}

    def _ensure_model(self, settings: dict | None = None):
        if self._batcher is not None:
            return
        if settings is None:
            settings = self._resolve_model_params()
        tokenizer_path = settings.get("tokenizer")
        self._tokenizer = load_tokenizer(tokenizer_path) \
            if tokenizer_path else ByteTokenizer()
        vocab = settings.get("vocab_size")
        # "flash" routes chunked admission through the Pallas kernel --
        # the long-context setting (2.5x dense at 8k on v5e).
        model = settings.get("model", "tiny")
        bases = {"tiny": llama.LlamaConfig.tiny,
                 "tiny-moe": llama.LlamaConfig.tiny_moe,
                 "llama3-1b": llama.LlamaConfig.llama3_1b,
                 "llama3-8b": llama.LlamaConfig.llama3_8b}
        if str(model) not in bases:
            raise ValueError(f"model={model!r}: one of {sorted(bases)}")
        base = bases[str(model)]()
        # An explicit vocab_size always wins (it must match the
        # tokenizer/checkpoint); otherwise tiny configs follow the
        # tokenizer and the llama configs keep their own vocab.
        if vocab is not None:
            base = dataclasses.replace(base, vocab_size=int(vocab))
        elif str(model).startswith("tiny"):
            base = dataclasses.replace(
                base, vocab_size=self._tokenizer.vocab_size)
        config = dataclasses.replace(
            base, max_seq=int(settings.get("max_seq", 256)),
            attention=str(settings.get("attention", "dense")))
        # ``decode_kernel`` selects the decode-attention backend in the
        # ops capability-probe vocabulary (ops.decode_backend):
        # paged-kernel / dense-flash force the Pallas kernel plane
        # (which one actually engages follows the cache's structure),
        # reference forces the dense einsum path, auto defers to the
        # extent threshold.  Domain-validated at create time
        # (analysis/params.py ELEMENT_PARAMETERS).
        decode_kernel = str(settings.get("decode_kernel",
                                         "auto")).strip().lower()
        kernel_to_attention = {"auto": "auto", "paged-kernel": "flash",
                               "dense-flash": "flash",
                               "reference": "dense"}
        if decode_kernel not in kernel_to_attention:
            raise ValueError(
                f"decode_kernel={decode_kernel!r}: one of "
                f"{'|'.join(sorted(kernel_to_attention))}")
        if decode_kernel != "auto":
            config = dataclasses.replace(
                config,
                decode_attention=kernel_to_attention[decode_kernel])
        params = _restore(
            llama.init_params(
                jax.random.PRNGKey(int(settings.get("seed", 0))), config),
            settings.get("checkpoint"))
        quantize = settings.get("quantize", False)
        normalized = str(quantize).strip().lower()
        if parse_bool(quantize) or normalized == "int8":
            # Weight-only int8 (models/quant.py): halves decode's HBM
            # stream; activations/cache stay bf16.
            from ..models.quant import quantize_params
            params = quantize_params(params)
        elif normalized not in ("false", "0", "no", "off", "none", ""):
            # A typo must not silently serve bf16 at half the promised
            # decode rate.
            raise ValueError(
                f"quantize={quantize!r}: use true/false or int8")
        # Requests beyond max_slots queue (sizing rationale: class
        # docstring).  The pipeline TransferLedger counts the one
        # explicit host fetch each retired device-loop block pays; the
        # chaos probe arms the ``decode_block`` injection point.
        ledger = self._ledger()
        kv_pages = settings.get("kv_pages")
        self._batcher = ContinuousBatcher(
            params, config,
            max_slots=int(settings.get("max_slots", 8)),
            decode_block=int(settings.get("decode_block", 1)),
            inflight=int(settings.get("inflight", 2)),
            decode_block_tokens=int(
                settings.get("decode_block_tokens", 0)),
            speculative=str(settings.get("speculative", "off")),
            spec_tokens=int(settings.get("spec_tokens", 4)),
            spec_window=int(settings.get("spec_window", 32)),
            kv_page_tokens=int(settings.get("kv_page_tokens", 0)),
            kv_pages=None if kv_pages is None else int(kv_pages),
            sample_top_k=int(settings.get("sample_top_k", 0)),
            prefix_cache=settings.get("prefix_cache", False),
            prefix_min_tokens=int(settings.get("prefix_min_tokens", 64)),
            spec_autoprobe=settings.get("spec_autoprobe", True),
            fetch=None if ledger is None
            else (lambda tree: ledger.fetch(tree, label="llm_block")),
            fault_probe=self._fault_probe,
            on_block=self._note_block)

    def _note_block(self, phase: str, slots: int) -> None:
        """Flight-recorder tap (ISSUE 10): every decode-block dispatch/
        retire lands on the pipeline's event ring (global events --
        no stream/frame: one block serves many), so serving cadence is
        on the same timeline as the frames in a black-box dump.  Runs
        on the element's decode worker thread; the ring is
        thread-safe and a missing recorder costs one getattr."""
        recorder = getattr(self.pipeline, "recorder", None)
        if recorder is not None:
            recorder.record("llm_block", None, None, phase,
                            None, {"slots": slots})

    def _make_request(self, stream_id, text,
                      request_params: dict) -> tuple[Request, list[int]]:
        system_prompt = request_params["system_prompt"]
        prompt = f"{system_prompt}{text}" if system_prompt else str(text)
        self._request_seq += 1
        collected: list[int] = []
        return Request(
            request_id=f"{stream_id}/{self._request_seq}",
            prompt_tokens=self._tokenizer.encode(prompt),
            max_new_tokens=request_params["max_new_tokens"],
            temperature=request_params["temperature"],
            eos_tokens=self._tokenizer.eos_tokens,
            emit=_collector(self._tokenizer, collected)), collected

    def process_frame_start(self, stream, complete, text=None, **inputs):
        self._start_worker()
        # Parameters resolve HERE (loop thread, current-stream context
        # intact); the worker consumes pre-resolved values.  The model
        # settings ride along until the first request builds it.  The
        # stream's QoS identity rides too (ISSUE 12): the batcher's
        # slot admission is the fourth plane of the unified scheduler.
        model_params = None if self._batcher is not None \
            else self._resolve_model_params()
        qos = getattr(self.pipeline, "qos", None)
        qos_info = (getattr(stream, "tenant", None),
                    getattr(stream, "qos_class", None),
                    0 if qos is None
                    else qos.class_rank(getattr(stream, "qos_class",
                                                None)))
        # Process fault domain (ISSUE 13): the frame identity keys the
        # journal's per-token commits, and an adopted frame's journaled
        # committed prefix resumes generation instead of re-running it.
        pipeline = getattr(self, "pipeline", None)
        frame = None
        current = getattr(pipeline, "current_frame", None)
        if callable(current):
            frame = current()
        journal_key = None
        resume = None
        if frame is not None:
            if getattr(pipeline, "journal", None) is not None \
                    and getattr(stream, "journal", False):
                journal_key = (str(stream.stream_id),
                               int(frame.frame_id))
            take = getattr(pipeline, "take_journal_resume", None)
            if callable(take):
                resume = take(stream.stream_id, frame.frame_id)
        self._work.put(("request", str(stream.stream_id), text, complete,
                        self._resolve_request_params(), model_params,
                        qos_info, journal_key, resume))

    def stop_stream(self, stream, stream_id):
        """Cancel the stream's outstanding requests: a frame parked here
        when its stream is destroyed must stop decoding (it would
        otherwise run to max_new_tokens in a device batch slot) and its
        parked ``complete`` must not fire later.  Routed through the
        worker queue -- the batcher and the completes registry are
        worker-owned."""
        if self._work is not None:
            self._work.put(("cancel", f"{stream.stream_id}/"))
        return StreamEvent.OKAY, {}

    def drain_requests(self):
        """Migrate-in-place for ``Pipeline.drain`` (ISSUE 13): cancel
        every live request (committed prefixes are already journaled
        token by token) and drop the parked frames without responding,
        leaving them undelivered in the journal -- the adopting peer
        replays each frame and its LLM request resumes at the
        committed prefix via ``ContinuousBatcher.resume_request``."""
        if self._work is not None:
            self._work.put(("drain",))

    # -- device worker -----------------------------------------------------

    def _start_worker(self):
        if self._work is None:
            self._work = queue.Queue()
            threading.Thread(target=self._worker, args=(self._work,),
                             daemon=True,
                             name=f"llm-worker-{self.name}").start()

    def _handle(self, item):
        """One queue item, on the worker thread.  A failing REQUEST
        (bad model parameter, broken checkpoint) errors ITS OWN frame
        and is swallowed -- one bad frame must not strand the others."""
        if item[0] == "request":
            (_, stream_id, text, complete, request_params, model_params,
             qos_info, journal_key, resume) = item
            try:
                self._ensure_model(model_params)
                request, collected = self._make_request(
                    stream_id, text, request_params)
                request.tenant, request.qos_class, request.qos_rank = \
                    qos_info
            except Exception as error:
                self.logger.exception("LLM request setup failed")
                complete(StreamEvent.ERROR,
                         {"diagnostic": f"llm: {error}"})
                return
            tokenizer, inner_emit = self._tokenizer, request.emit
            journal = getattr(self.pipeline, "journal", None) \
                if journal_key is not None else None

            def emit(request_id, token, finished):
                inner_emit(request_id, token, finished)
                if journal is not None:
                    # Committed-prefix commit point (ISSUE 13): every
                    # emitted token becomes durable, so an adopter
                    # resumes generation exactly here.  Worker-thread
                    # safe; the fsync is batched.
                    journal.llm_token(journal_key[0], journal_key[1],
                                      int(token))
                if finished:
                    self._completes.pop(request_id, None)
                    complete(StreamEvent.OKAY,
                             {"text": tokenizer.decode(collected)})

            request.emit = emit
            self._completes[request.request_id] = complete
            self._batcher.submit(request)
            if resume:
                # Adopted frame: fold the journaled committed prefix
                # in (prompt + committed re-prefill, budget arithmetic
                # preserved) and pre-seed the collector, so the final
                # text is byte-identical to an uninterrupted run at
                # temperature 0 -- tokens already streamed are never
                # re-generated.
                eos = set(self._tokenizer.eos_tokens)
                collected.extend(int(token) for token in resume
                                 if int(token) not in eos)
                if not self._batcher.resume_request(request, resume):
                    # The prefix already finished the request (the
                    # process died between the final emit and
                    # delivery): complete from the committed tokens
                    # -- resuming would decode a spurious tail.
                    self._completes.pop(request.request_id, None)
                    complete(StreamEvent.OKAY,
                             {"text": tokenizer.decode(collected)})
        elif item[0] == "drain":
            # Cooperative drain (ISSUE 13): every live request's
            # committed prefix is already journaled per token; cancel
            # them and DROP the parked frames -- no response is sent
            # (the adopter's replay is the response), so the client
            # sees each result exactly once, from the peer.
            completes, self._completes = self._completes, {}
            for request_id, complete in completes.items():
                if self._batcher is not None:
                    self._batcher.cancel(request_id)
                complete(StreamEvent.DROP_FRAME, {})
        else:                           # ("cancel", stream prefix)
            prefix = item[1]
            for request_id in [rid for rid in self._completes
                               if str(rid).startswith(prefix)]:
                self._completes.pop(request_id, None)
                if self._batcher is not None:
                    self._batcher.cancel(request_id)

    def _drain_work(self, work: "queue.Queue"):
        while True:
            try:
                self._handle(work.get_nowait())
            except queue.Empty:
                return

    def _ledger(self):
        """The pipeline's TransferLedger (None outside a pipeline --
        direct construction in tests)."""
        return getattr(getattr(self, "pipeline", None),
                       "transfer_ledger", None)

    def _fault_probe(self, point: str):
        """Chaos injection point ``decode_block`` (faults/plan.py):
        consulted by the batcher before every device-loop block
        dispatch.  A matched rule with ``delay_ms`` hangs the
        dispatch; without, it raises FaultInjected standing in for the
        XLA error a dying chip surfaces mid-generation -- driving the
        same recovery path (``ContinuousBatcher.recover``)."""
        plan = getattr(getattr(self, "pipeline", None), "_faults", None)
        if plan is None:
            return
        rule = plan.should(point, target=self.name)
        if rule is None:
            return
        if rule.delay_ms:
            time.sleep(rule.delay_ms / 1000.0)
            return
        from ..faults import FaultInjected
        raise FaultInjected(
            f"{point} kill injected at {self.name}")

    def _tick(self, batcher):
        """One batcher step.  Device-loop ticks run under the
        transfer-ledger guard: on hardware backends a stray per-token
        device-to-host sync then RAISES under ``transfer_guard:
        disallow`` -- the batcher's only legal host read is the ledger-
        counted per-block fetch it was built with."""
        ledger = self._ledger()
        if batcher.device_loop and ledger is not None:
            with ledger.guard():
                batcher.step()
        else:
            batcher.step()
        self._recover_streak = 0
        self._publish_serving_stats(batcher)

    def _recover(self, batcher, error) -> bool:
        """Replay-from-last-emitted-block after a device-level failure:
        rebuild the cache/page pool and re-queue every live request at
        its committed prefix (ContinuousBatcher.recover).  Gives up --
        letting the worker's error path fail the parked frames -- on
        the THIRD consecutive failed tick (a persistently dying
        device), resetting the streak so the next workload gets its
        own replay attempts."""
        self._recover_streak += 1
        if self._recover_streak > 2:
            self._recover_streak = 0
            return False
        revived = batcher.recover()
        self.logger.warning(
            "LLM decode failed (%s); replaying %d request(s) from "
            "their last emitted block", error, revived)
        telemetry = getattr(self.pipeline, "telemetry", None)
        if telemetry is not None:
            telemetry.registry.count("llm_loop_recoveries")
        return True

    def _publish_serving_stats(self, batcher):
        """Per-request latency histograms + speculation counters into
        the telemetry plane (registry is thread-safe; share updates
        marshal onto the event loop)."""
        telemetry = getattr(self.pipeline, "telemetry", None)
        stats = batcher.take_request_stats()
        if telemetry is not None:
            for entry in stats:
                # Tenant/class labels (ISSUE 19): the per-tenant SLO
                # view needs decode latency split the same way the
                # gateway splits e2e.  Unlabeled when the request
                # carried no QoS context (direct element use).
                labels = {}
                if entry.get("tenant"):
                    labels["tenant"] = str(entry["tenant"])
                if entry.get("cls"):
                    labels["cls"] = str(entry["cls"])
                telemetry.registry.observe("llm_ttft_ms",
                                           entry["ttft_ms"], **labels)
                if entry["tokens"] > 1:
                    telemetry.registry.observe("llm_tpot_ms",
                                               entry["tpot_ms"],
                                               **labels)
        changed = False
        hits = batcher.prefix_hits
        lookups = batcher.prefix_lookups
        if hits != self._published_prefix_hits \
                or lookups != self._published_prefix_lookups:
            changed = True
            if telemetry is not None:
                telemetry.registry.count(
                    "llm_prefix_hits",
                    hits - self._published_prefix_hits)
                telemetry.registry.count(
                    "llm_prefix_lookups",
                    lookups - self._published_prefix_lookups)
            self._published_prefix_hits = hits
            self._published_prefix_lookups = lookups
        accepted = batcher.accepted_tokens
        drafted = batcher.draft_tokens
        if accepted != self._published_accepted \
                or drafted != self._published_drafted:
            changed = True
            if telemetry is not None:
                telemetry.registry.count(
                    "llm_accepted_tokens",
                    accepted - self._published_accepted)
                telemetry.registry.count(
                    "llm_draft_tokens",
                    drafted - self._published_drafted)
            self._published_accepted = accepted
            self._published_drafted = drafted
        if not changed:
            return
        pipeline = self.pipeline

        def update_share():
            pipeline.ec_producer.update("llm_accepted_tokens", accepted)
            pipeline.ec_producer.update("llm_draft_tokens", drafted)
            pipeline.ec_producer.update("llm_prefix_hits", hits)
            pipeline.ec_producer.update("llm_prefix_lookups", lookups)
            pipeline.ec_producer.update("llm_spec_probe_ratio",
                                        batcher.spec_probe_ratio)
        pipeline.runtime.engine.post_deferred(update_share)

    def _worker(self, work: "queue.Queue"):
        """Owns every device interaction: lazy model build, admission,
        the decode loop, retire fetches.  Blocks on the queue while
        idle; while decoding, new queue items (requests from frames
        resumed meanwhile, stream cancels) are drained BETWEEN ticks so
        they join the live device batch."""
        while True:
            item = work.get()
            with self._device_lock:
                try:
                    self._handle(item)
                    self._drain_work(work)
                    batcher = self._batcher
                    while batcher is not None and (
                            batcher.active_count or batcher.queue_depth
                            or batcher.blocks_in_flight):
                        try:
                            self._tick(batcher)
                        except Exception as error:
                            # Device loss mid-generation: replay every
                            # live request from its last emitted block
                            # (ISSUE 8) before the error path below
                            # gets to fail the parked frames.
                            if not self._recover(batcher, error):
                                raise
                        self._drain_work(work)
                except Exception as error:
                    # A failing decode tick must FAIL the parked frames,
                    # not leave them parked forever -- the async
                    # analogue of the engine's per-element try/except.
                    # Their requests are CANCELLED too: an errored
                    # frame's request left active would keep decoding
                    # to max_new_tokens in a device batch slot,
                    # crowding out the next frames' requests.
                    self.logger.exception("LLM worker failed")
                    completes, self._completes = self._completes, {}
                    for request_id, complete in completes.items():
                        if self._batcher is not None:
                            self._batcher.cancel(request_id)
                        complete(StreamEvent.ERROR,
                                 {"diagnostic": f"llm worker: {error}"})

    def process_frame(self, stream, text=None, **inputs):
        """Blocking path (``synchronous: true`` or direct invocation):
        drains the batcher inline, serialized against the async worker
        through the device lock."""
        with self._device_lock:
            self._ensure_model()
            request, collected = self._make_request(
                str(stream.stream_id), text, self._resolve_request_params())
            self._batcher.submit(request)
            self._batcher.run_until_drained()
            return StreamEvent.OKAY, {
                "text": self._tokenizer.decode(collected)}
