"""LLM serving: actor + pipeline element (BASELINE config 3; reference
equivalent: examples/llm/elements.py:92-212, which forwards chat turns to
an external Ollama/CUDA server via LangChain).

Here serving is native to the framework:

- :class:`LLMService` is an Actor owning a :class:`ContinuousBatcher`
  (models/batching.py): weights and the batched KV cache live in HBM;
  any number of remote callers stream generations concurrently.  Wire
  protocol on ``topic/in``::

      (generate response_topic request_id prompt max_new_tokens temp)

  replies on ``response_topic``::

      (token request_id fragment)     per decode step
      (complete request_id full_text)

  The decode loop rides the event engine: while work is pending the
  service re-posts its pump, so decode ticks interleave with message
  handling instead of blocking the process (the "batching mailbox
  between the actor layer and the device loop" flagged in SURVEY §7).

- :class:`LLM` is a PipelineElement producing ``text`` out of ``text``
  frames, hosting its own model in-process.  To share one model (one
  set of HBM weights) across many pipelines, wrap this element in a
  small pipeline and reference it from the others as a remote stage
  (``deploy: remote``) -- the framework's pause/resume continuation
  carries the frame across, exactly like any other remote element.
"""

from __future__ import annotations

import dataclasses

import jax

from ..models import llama
from ..models.batching import ContinuousBatcher, Request
from ..models.checkpoint import maybe_restore as _restore
from ..models.tokenizer import ByteTokenizer, load_tokenizer
from ..pipeline import PipelineElement, StreamEvent
from ..services import Actor
from ..utils import generate, get_logger, parse_bool, parse_number

__all__ = ["LLMService", "LLM", "DetectionCaption", "PROTOCOL_LLM"]

_logger = get_logger("aiko.llm")

PROTOCOL_LLM = "llm:0"


def _collector(tokenizer, collected: list):
    """Emit callback appending non-EOS tokens to ``collected``."""
    eos = set(tokenizer.eos_tokens)

    def emit(request_id, token, finished):
        if token not in eos:
            collected.append(token)
    return emit


class LLMService(Actor):
    """Continuous-batching generation server."""

    def __init__(self, name: str = "llm", runtime=None,
                 config: llama.LlamaConfig | None = None,
                 params=None, tokenizer=None, max_slots: int = 8,
                 checkpoint: str | None = None, seed: int = 0,
                 decode_block: int = 1, inflight: int = 2):
        super().__init__(name, PROTOCOL_LLM, tags=["ec=true"],
                         runtime=runtime)
        if config is None:
            config = llama.LlamaConfig.tiny()
        if params is None:
            params = _restore(
                llama.init_params(jax.random.PRNGKey(seed), config),
                checkpoint)
        self.tokenizer = tokenizer or ByteTokenizer()
        # decode_block > 1 with inflight > 1 is the pipelined serving
        # path (fused multi-step blocks chained device-side) -- the same
        # configuration the bench runs; the wire-facing server defaults
        # stay at one-step dispatches so token streaming is per-step.
        self.batcher = ContinuousBatcher(params, config,
                                         max_slots=max_slots,
                                         decode_block=decode_block,
                                         inflight=inflight)
        # Keyed by (response_topic, request_id): two callers independently
        # choosing the same request_id (both starting at "1") must not
        # collide -- the response topic is the caller's identity.
        self._texts: dict[tuple[str, str], list[int]] = {}
        self._pumping = False
        self.share.update({"model_layers": config.n_layers,
                           "max_slots": max_slots,
                           "active": 0, "queued": 0,
                           "tokens_emitted": 0})

    # -- wire API ----------------------------------------------------------

    def generate(self, response_topic, request_id, prompt,
                 max_new_tokens="128", temperature="0"):
        """(generate response_topic request_id prompt max tokens temp)"""
        key = (str(response_topic), str(request_id))
        self._texts[key] = []
        self.batcher.submit(Request(
            request_id="\x00".join(key),
            prompt_tokens=self.tokenizer.encode(str(prompt)),
            max_new_tokens=int(parse_number(max_new_tokens, 128)),
            temperature=float(parse_number(temperature, 0.0)),
            eos_tokens=self.tokenizer.eos_tokens,
            emit=self._on_token))
        self._start_pump()

    # -- decode pump -------------------------------------------------------

    def _start_pump(self):
        if not self._pumping:
            self._pumping = True
            self.runtime.engine.post_deferred(self._pump)

    def _pump(self):
        active = self.batcher.step()
        self.ec_producer.update("active", self.batcher.active_count)
        self.ec_producer.update("queued", self.batcher.queue_depth)
        self.ec_producer.update("tokens_emitted",
                                self.batcher.tokens_emitted)
        if active or self.batcher.queue_depth \
                or self.batcher.blocks_in_flight:
            # Deferred, not synchronous: new (generate ...) messages
            # interleave between decode ticks and join the batch.
            self.runtime.engine.post_deferred(self._pump)
        else:
            self._pumping = False

    def _on_token(self, batcher_id: str, token: int, finished: bool):
        reply_topic, _, request_id = batcher_id.partition("\x00")
        key = (reply_topic, request_id)
        tokens = self._texts.setdefault(key, [])
        if token not in self.tokenizer.eos_tokens:
            tokens.append(token)
            fragment = self.tokenizer.decode([token])
            self.runtime.message.publish(
                reply_topic,
                generate("token", [request_id, fragment]))
        if finished:
            text = self.tokenizer.decode(tokens)
            self.runtime.message.publish(
                reply_topic, generate("complete", [request_id, text]))
            self._texts.pop(key, None)

    # -- local API ---------------------------------------------------------

    def generate_local(self, prompt: str, max_new_tokens: int = 128,
                       temperature: float = 0.0) -> str:
        """Synchronous generation (drains the batcher inline): for
        single-process callers and tests."""
        collected: list[int] = []
        self.batcher.submit(Request(
            request_id="local",
            prompt_tokens=self.tokenizer.encode(prompt),
            max_new_tokens=max_new_tokens, temperature=temperature,
            eos_tokens=self.tokenizer.eos_tokens,
            emit=_collector(self.tokenizer, collected)))
        self.batcher.run_until_drained()
        return self.tokenizer.decode(collected)


class DetectionCaption(PipelineElement):
    """``detections`` (Detector output dicts) -> ``text`` prompt for a
    downstream LLM stage -- the detect->describe bridge of the
    video->detect->caption pipeline (BASELINE config 4; reference
    equivalent: examples/llm/elements.py:204 Detection, which formats
    detections into the Ollama prompt).

    Parameter ``template`` wraps the summary (``{detections}``
    placeholder)."""

    def process_frame(self, stream, detections=None, **inputs):
        detections = detections or []
        counts: dict[str, int] = {}
        for detection in detections:
            name = str(detection.get("class", "object"))
            counts[name] = counts.get(name, 0) + 1
        summary = ", ".join(
            f"{count} {name}" if count > 1 else name
            for name, count in sorted(counts.items())) or "nothing"
        template, _ = self.get_parameter(
            "template", "Describe a scene containing: {detections}.")
        # Plain replace, not str.format: templates may legitimately
        # contain literal braces (JSON-shaped prompts).
        return StreamEvent.OKAY, {
            "text": str(template).replace("{detections}", summary)}


class LLM(PipelineElement):
    """``text`` -> generated ``text``.

    Parameters: ``max_new_tokens``, ``temperature``, ``system_prompt``,
    ``tokenizer`` (HF directory), ``checkpoint`` (orbax dir),
    ``vocab_size``/``max_seq``/``seed`` (local tiny config),
    ``attention`` (``dense`` | ``flash`` -- the Pallas long-context
    prefill path, 2.5x dense at 8k context), ``quantize`` (weight-only
    int8: halves decode's HBM stream), ``decode_block`` (fuse N decode
    steps per device dispatch: amortizes host round trips), ``inflight``
    (keep N fused blocks in flight, chained device-side: hides the
    dispatch round trip behind device compute), ``max_slots`` (device
    batch width: size to the expected concurrent-frame count; decode is
    weight-HBM-bound at short context, so wider batches decode more
    frames' requests per block at nearly the same step time).

    ASYNC by default: each frame submits its request to the shared
    :class:`ContinuousBatcher` and parks; the batcher pump rides the
    event engine, so decode ticks interleave with message handling and
    with OTHER frames' stages -- requests from many in-flight
    frames/streams decode together in one device batch (continuous
    batching across frames, not per-frame drains).  Set parameter
    ``synchronous: true`` for the blocking per-frame path.
    """

    is_async = True

    def __init__(self, context):
        super().__init__(context)
        self._batcher: ContinuousBatcher | None = None
        self._tokenizer = None
        self._pumping = False
        self._request_seq = 0
        # request_id -> complete for parked async frames, so a failing
        # pump can error them out instead of leaving them parked.
        self._completes: dict = {}

    def _ensure_model(self):
        if self._batcher is not None:
            return
        checkpoint, _ = self.get_parameter("checkpoint", None)
        tokenizer_path, found = self.get_parameter("tokenizer", None)
        self._tokenizer = load_tokenizer(tokenizer_path) \
            if found and tokenizer_path else ByteTokenizer()
        vocab, vocab_found = self.get_parameter("vocab_size", None)
        max_seq, _ = self.get_parameter("max_seq", 256)
        seed, _ = self.get_parameter("seed", 0)
        # "flash" routes chunked admission through the Pallas kernel --
        # the long-context setting (2.5x dense at 8k on v5e).
        attention, _ = self.get_parameter("attention", "dense")
        model, _ = self.get_parameter("model", "tiny")
        bases = {"tiny": llama.LlamaConfig.tiny,
                 "tiny-moe": llama.LlamaConfig.tiny_moe,
                 "llama3-1b": llama.LlamaConfig.llama3_1b,
                 "llama3-8b": llama.LlamaConfig.llama3_8b}
        if str(model) not in bases:
            raise ValueError(f"model={model!r}: one of {sorted(bases)}")
        base = bases[str(model)]()
        # An explicit vocab_size always wins (it must match the
        # tokenizer/checkpoint); otherwise tiny configs follow the
        # tokenizer and the llama configs keep their own vocab.
        if vocab_found and vocab is not None:
            base = dataclasses.replace(base, vocab_size=int(vocab))
        elif str(model).startswith("tiny"):
            base = dataclasses.replace(
                base, vocab_size=self._tokenizer.vocab_size)
        config = dataclasses.replace(base, max_seq=int(max_seq),
                                     attention=str(attention))
        params = _restore(
            llama.init_params(jax.random.PRNGKey(int(seed)), config),
            checkpoint)
        quantize, _ = self.get_parameter("quantize", False)
        normalized = str(quantize).strip().lower()
        if parse_bool(quantize) or normalized == "int8":
            # Weight-only int8 (models/quant.py): halves decode's HBM
            # stream; activations/cache stay bf16.
            from ..models.quant import quantize_params
            params = quantize_params(params)
        elif normalized not in ("false", "0", "no", "off", "none", ""):
            # A typo must not silently serve bf16 at half the promised
            # decode rate.
            raise ValueError(
                f"quantize={quantize!r}: use true/false or int8")
        decode_block, _ = self.get_parameter("decode_block", 1)
        inflight, _ = self.get_parameter("inflight", 2)
        # Requests beyond max_slots queue (sizing rationale: class
        # docstring).
        max_slots, _ = self.get_parameter("max_slots", 8)
        self._batcher = ContinuousBatcher(
            params, config, max_slots=int(max_slots),
            decode_block=int(decode_block), inflight=int(inflight))

    def _make_request(self, stream, text) -> tuple[Request, list[int]]:
        max_new, _ = self.get_parameter("max_new_tokens", 32)
        temperature, _ = self.get_parameter("temperature", 0.0)
        system_prompt, _ = self.get_parameter("system_prompt", "")
        prompt = f"{system_prompt}{text}" if system_prompt else str(text)
        self._request_seq += 1
        collected: list[int] = []
        return Request(
            request_id=f"{stream.stream_id}/{self._request_seq}",
            prompt_tokens=self._tokenizer.encode(prompt),
            max_new_tokens=int(max_new), temperature=float(temperature),
            eos_tokens=self._tokenizer.eos_tokens,
            emit=_collector(self._tokenizer, collected)), collected

    def process_frame_start(self, stream, complete, text=None, **inputs):
        self._ensure_model()
        request, collected = self._make_request(stream, text)
        tokenizer, inner_emit = self._tokenizer, request.emit

        def emit(request_id, token, finished):
            inner_emit(request_id, token, finished)
            if finished:
                self._completes.pop(request_id, None)
                complete(StreamEvent.OKAY,
                         {"text": tokenizer.decode(collected)})

        request.emit = emit
        self._completes[request.request_id] = complete
        self._batcher.submit(request)
        self._start_pump()

    def stop_stream(self, stream, stream_id):
        """Cancel the stream's outstanding requests: a frame parked here
        when its stream is destroyed must stop decoding (it would
        otherwise run to max_new_tokens in a device batch slot) and its
        parked ``complete`` must not fire later."""
        prefix = f"{stream.stream_id}/"
        for request_id in [rid for rid in self._completes
                           if str(rid).startswith(prefix)]:
            self._completes.pop(request_id, None)
            if self._batcher is not None:
                self._batcher.cancel(request_id)
        return StreamEvent.OKAY, {}

    def _start_pump(self):
        if not self._pumping:
            self._pumping = True
            self.pipeline.runtime.engine.post_deferred(self._pump)

    def _pump(self):
        batcher = self._batcher
        if batcher is None:             # stopped mid-flight
            self._pumping = False
            return
        try:
            batcher.step()
        except Exception as error:
            # A decode tick failing (device error, bad checkpoint
            # shapes) must FAIL the parked frames, not silently stop
            # the pump with them parked forever -- the async analogue
            # of the engine's per-element try/except.
            self.logger.exception("LLM pump step failed")
            self._pumping = False
            completes, self._completes = self._completes, {}
            for complete in completes.values():
                complete(StreamEvent.ERROR,
                         {"diagnostic": f"llm decode failed: {error}"})
            return
        if (batcher.active_count or batcher.queue_depth
                or batcher.blocks_in_flight):
            # Deferred so in-flight frames' submits land between decode
            # ticks and batch together.
            self.pipeline.runtime.engine.post_deferred(self._pump)
        else:
            self._pumping = False

    def process_frame(self, stream, text=None, **inputs):
        """Blocking path (``synchronous: true`` or direct invocation):
        drains the batcher inline."""
        self._ensure_model()
        request, collected = self._make_request(stream, text)
        self._batcher.submit(request)
        self._batcher.run_until_drained()
        return StreamEvent.OKAY, {"text": self._tokenizer.decode(collected)}
