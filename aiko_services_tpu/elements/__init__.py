from .common import Mock, NoOp, Identity, Terminate
from .scheme_file import DataSchemeFile
from .text import (TextReadFile, TextWriteFile, TextTransform, TextSample,
                   TextOutput)
from .observe import Inspect, Metrics
from .expression import Expression, AllOutputs, evaluate_expression
from .control import Loop
