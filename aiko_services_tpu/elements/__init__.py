from .common import Mock, NoOp, Identity, Terminate
from .scheme_file import DataSchemeFile
from .scheme_zmq import (DataSchemeZMQ, TextReadZMQ, TextWriteZMQ,
                         ImageReadZMQ, ImageWriteZMQ)
from .scheme_tty import DataSchemeTTY, TextReadTTY, TextWriteTTY
from .text import (TextReadFile, TextWriteFile, TextTransform, TextSample,
                   TextFilter, TextOutput)
from .image import (ImageReadFile, ImageWriteFile, ImageResize,
                    ImageOverlay, ImageOutput, image_to_array,
                    array_to_image)
from .video import (VideoReadFile, VideoWriteFile, VideoSample,
                    VideoOutput, VideoReadWebcam)
from .audio import (AudioReadFile, AudioWriteFile, AudioFraming,
                    AudioResampler, AudioFFT, AudioGraphXY, AudioOutput,
                    read_wav, write_wav)
from .audio_live import (MicrophoneRead, SpeakerWrite, DataSchemeMic,
                         DataSchemeSpeaker)
from .scheme_rtsp import DataSchemeRTSP, VideoReadRTSP, VideoWriteRTSP
from .scheme_tensor import (DataSchemeTensorPipe, TensorReadPipe,
                            TensorWritePipe)
from .detect import Detector
from .vision import FaceDetect, ArucoMarkerDetect
from .llm import LLM, LLMService, PROTOCOL_LLM
from .speech import ASR, TTS
from .observe import Inspect, Metrics
from .expression import Expression, AllOutputs, evaluate_expression
from .control import Loop
