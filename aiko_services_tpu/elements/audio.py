"""Audio elements (reference: src/aiko_services/elements/media/
audio_io.py): file read/write, framing, filter, resample, FFT.

File I/O uses the stdlib ``wave`` module (PCM16 WAV -- soundfile is not
in this environment; the reference used soundfile/pyaudio/sounddevice,
audio_io.py:75-205).  All DSP -- windowing, resampling, FFT -- runs as
jax ops on device instead of numpy on host.
"""

from __future__ import annotations

import os
import wave

import numpy as np

import jax
import jax.numpy as jnp

from ..models.batching import MicroBatchElement, pad_to_bucket
from ..pipeline import DataSource, DataTarget, PipelineElement, StreamEvent
from .scheme_file import DataSchemeFile

__all__ = ["AudioReadFile", "AudioWriteFile", "AudioFraming",
           "AudioResampler", "AudioFFT", "AudioGraphXY", "AudioOutput",
           "read_wav", "write_wav"]


def read_wav(path) -> tuple[np.ndarray, int]:
    """PCM16 WAV -> (float32 samples [N, C] in -1..1, sample_rate)."""
    with wave.open(os.fspath(path), "rb") as fh:
        rate = fh.getframerate()
        channels = fh.getnchannels()
        width = fh.getsampwidth()
        raw = fh.readframes(fh.getnframes())
    if width != 2:
        raise ValueError(f"{path}: only PCM16 WAV supported, got "
                         f"{8 * width}-bit")
    samples = np.frombuffer(raw, dtype="<i2").astype(np.float32) / 32768.0
    return samples.reshape(-1, channels), rate


def write_wav(path, samples, rate: int):
    """float32 samples [N] or [N, C] in -1..1 -> PCM16 WAV."""
    array = np.asarray(samples, dtype=np.float32)
    if array.ndim == 1:
        array = array[:, None]
    data = (np.clip(array, -1.0, 1.0) * 32767.0).astype("<i2")
    with wave.open(os.fspath(path), "wb") as fh:
        fh.setnchannels(array.shape[1])
        fh.setsampwidth(2)
        fh.setframerate(int(rate))
        fh.writeframes(data.tobytes())


class AudioReadFile(DataSource):
    """Reads WAV file(s); emits ``audio`` [N, C] jax array +
    ``sample_rate`` (reference audio_io.py:95-205)."""

    def process_frame(self, stream, **inputs):
        path = inputs.get("path")
        try:
            samples, rate = read_wav(path)
        except (OSError, ValueError, wave.Error) as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return StreamEvent.OKAY, {"audio": jnp.asarray(samples),
                                  "sample_rate": rate, "path": path}


class AudioWriteFile(DataTarget):
    """Writes ``audio`` to a WAV path (reference speech_elements.py:88)."""

    host_inputs = ("audio",)    # sink: the engine fetches explicitly

    def process_frame(self, stream, audio=None, sample_rate=16000,
                      **inputs):
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeFile):
            return StreamEvent.ERROR, {
                "diagnostic": "AudioWriteFile requires file:// targets"}
        path = scheme.target_path(stream)
        try:
            write_wav(path, audio, int(sample_rate))
        except (OSError, ValueError, wave.Error) as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return StreamEvent.OKAY, {"path": path}


class AudioFraming(PipelineElement):
    """Splits ``audio`` into fixed windows with hop (sliding window like
    the reference's LRU audio framing, speech_elements.py:53-84); emits
    ``frames`` [num_windows, window, C]."""

    def process_frame(self, stream, audio=None, sample_rate=16000,
                      **inputs):
        window, _ = self.get_parameter("window", 400)
        hop, _ = self.get_parameter("hop", 160)
        window, hop = int(window), int(hop)
        audio = jnp.asarray(audio)
        if audio.ndim == 1:
            audio = audio[:, None]
        n = audio.shape[0]
        if n < window:
            audio = jnp.pad(audio, ((0, window - n), (0, 0)))
            n = window
        starts = jnp.arange(0, n - window + 1, hop)
        frames = jax.vmap(
            lambda s: jax.lax.dynamic_slice_in_dim(audio, s, window))(
            starts)
        return StreamEvent.OKAY, {"frames": frames,
                                  "sample_rate": sample_rate}


class AudioResampler(PipelineElement):
    """Linear resample to ``target_rate`` -- jax on device (reference
    audio_io.py:237-299 used numpy)."""

    def process_frame(self, stream, audio=None, sample_rate=16000,
                      **inputs):
        target, _ = self.get_parameter("target_rate", 16000)
        target = int(target)
        rate = int(sample_rate)
        audio = jnp.asarray(audio)
        if rate == target:
            return StreamEvent.OKAY, {"audio": audio,
                                      "sample_rate": target}
        squeeze = audio.ndim == 1
        if squeeze:
            audio = audio[:, None]
        new_length = int(round(audio.shape[0] * target / rate))
        resampled = jax.image.resize(
            audio.astype(jnp.float32), (new_length, audio.shape[1]),
            method="linear")
        if squeeze:
            resampled = resampled[:, 0]
        return StreamEvent.OKAY, {"audio": resampled,
                                  "sample_rate": target}


class AudioFFT(MicroBatchElement, PipelineElement):
    """Magnitude spectrum per window of ``frames`` (reference
    audio_io.py:299-334's PE_FFT).

    ASYNC by default: same-shape window batches parked here -- from
    every stream -- transform together as one batched device FFT
    (MicroBatcher), each frame's spectrum row staying device-resident
    for downstream device stages.  ``synchronous: true`` for the
    blocking path.
    """

    is_async = True
    device_resident = True

    @staticmethod
    def _spectrum(frames):
        mono = frames.mean(axis=-1) if frames.ndim >= 3 else frames
        return jnp.abs(jnp.fft.rfft(mono.astype(jnp.float32), axis=-1))

    def process_frame(self, stream, frames=None, sample_rate=16000,
                      **inputs):
        return StreamEvent.OKAY, {
            "spectrum": self._spectrum(jnp.asarray(frames)),
            "sample_rate": sample_rate}

    def device_fn(self, stream):
        """Fused-segment contract: the FFT is pure device math;
        ``sample_rate`` is not consumed by the trace, so the engine
        passes it through host-side unchanged (type preserved)."""
        from ..pipeline import DeviceFn
        return DeviceFn(
            fn=lambda frames: {
                "spectrum": self._spectrum(jnp.asarray(frames))},
            inputs=("frames",), outputs=("spectrum",))

    def process_frame_start(self, stream, complete, frames=None,
                            sample_rate=16000, **inputs):
        self.submit_microbatch(complete, (frames, sample_rate),
                               diagnostic="bad frames")

    def batch_key(self, payload):
        frames, _ = payload
        if not hasattr(frames, "shape"):    # array-likes: numpy metadata
            frames = np.asarray(frames)
        return tuple(frames.shape), str(frames.dtype)

    def batch_run(self, context, key, payloads):
        windows = pad_to_bucket([frames for frames, _ in payloads])
        if all(isinstance(frames, np.ndarray) for frames in windows):
            batch = jnp.asarray(np.stack(windows))  # one upload
        else:
            batch = jnp.stack([jnp.asarray(frames)
                               for frames in windows])
        # The leading batch dim shifts the mono check by one: a batch
        # of [windows, window, C] items is 4-d.
        mono = batch.mean(axis=-1) if batch.ndim >= 4 else batch
        return jnp.abs(jnp.fft.rfft(mono.astype(jnp.float32), axis=-1))

    def batch_finish(self, context, key, entries, result):
        for row, (complete, (_, sample_rate)) in enumerate(entries):
            complete(StreamEvent.OKAY, {"spectrum": result[row],
                                        "sample_rate": sample_rate})


class AudioGraphXY(PipelineElement):
    """Render the magnitude spectrum as an amplitude-vs-frequency plot
    IMAGE (reference audio_io.py:334 PE_GraphXY, which pygal-renders a
    PNG and cv2.imshows it in a window; here the plot is an ordinary
    ``image`` array [height, width, 3] uint8, so it composes with the
    existing image sinks -- ImageWriteFile, VideoWriteRTSP, overlays --
    instead of needing a display).

    Input ``spectrum`` [windows, bins] (AudioFFT output; the windows
    are averaged) or [bins].  Parameters: ``width``/``height`` (plot
    pixels), ``max_frequency`` (clip the x axis; default Nyquist).
    Outputs the plot as ``image`` and passes ``spectrum`` through.
    """

    # numpy plotting is host work: one counted engine fetch, not an
    # implicit sync of the device-resident AudioFFT output.
    host_inputs = ("spectrum",)

    def process_frame(self, stream, spectrum=None, sample_rate=16000,
                      **inputs):
        data = np.asarray(spectrum, dtype=np.float32)
        if data.ndim == 2:
            data = data.mean(axis=0)
        bins = data.shape[0]
        width = int(self.get_parameter("width", 512)[0])
        height = int(self.get_parameter("height", 256)[0])
        nyquist = float(sample_rate) / 2.0
        max_frequency, found = self.get_parameter("max_frequency", None)
        if found and max_frequency:
            keep = max(1, int(bins * min(1.0, float(max_frequency)
                                         / max(nyquist, 1e-9))))
            data = data[:keep]
            bins = keep
        # Per-column peak over each column's bin range (reduceat gives
        # the vectorized ragged max), scaled to pixel heights.
        edges = np.floor(np.linspace(0, bins, width,
                                     endpoint=False)).astype(np.int64)
        edges = np.maximum.accumulate(edges)     # monotonic for reduceat
        columns = np.maximum.reduceat(data, edges) if bins >= width \
            else data[np.minimum(edges, bins - 1)]
        peak = float(columns.max())
        heights = np.zeros(width, dtype=np.int64) if peak <= 0 else \
            np.round(columns / peak * (height - 1)).astype(np.int64)
        rows = np.arange(height)[:, None]        # row 0 = top
        bars = rows >= (height - 1 - heights)[None, :]
        image = np.zeros((height, width, 3), dtype=np.uint8)
        image[..., :] = (16, 16, 32)             # background
        image[bars] = (64, 200, 120)             # spectrum bars
        image[-1, :, :] = 255                    # frequency axis
        return StreamEvent.OKAY, {"image": image, "spectrum": spectrum,
                                  "sample_rate": sample_rate}


class AudioOutput(PipelineElement):
    """Logs audio shape; passthrough (reference audio_io.py:75-95)."""

    def process_frame(self, stream, audio=None, **inputs):
        if audio is not None:
            self.logger.info("audio %s", tuple(getattr(audio, "shape",
                                                       ())))
        return StreamEvent.OKAY, {"audio": audio}
