"""Detection PipelineElement (BASELINE config 2; reference equivalent:
examples/yolo/yolo.py:50-93 YoloDetector wrapping ultralytics/torch).

``Detector`` hosts the framework's JAX detector (models/detector.py) on
its mesh: weights init (or restore from a checkpoint directory
parameter) at first use, forward+decode+NMS jitted once per input
resolution via the element JitCache, detections emitted as the same
overlay dict the reference's elements feed ImageOverlay
(yolo.py:80-92).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models import detector
from ..pipeline import StreamEvent, TPUElement

__all__ = ["Detector"]

_DEFAULT_CLASSES = ["person", "robot_dog", "ball", "obstacle"]


class Detector(TPUElement):
    """image [H, W, 3] uint8/float -> ``overlay`` rectangles +
    ``detections`` list.

    Parameters: ``num_classes``, ``class_names``, ``score_threshold``,
    ``checkpoint`` (optional orbax directory with {"params": ...}).
    """

    def __init__(self, context):
        super().__init__(context)
        self._params = None
        self._config = None
        self._detect = None

    def on_replacement(self):
        super().on_replacement()
        self._params = None             # _ensure_model reloads on the
        self._detect = None             # replacement submesh

    def _ensure_model(self):
        if self._params is not None:
            return
        names, names_found = self.get_parameter("class_names",
                                                _DEFAULT_CLASSES)
        threshold, _ = self.get_parameter("score_threshold", 0.25)
        width, _ = self.get_parameter("width", 8)
        self._class_names = list(names)
        num_classes, nc_found = self.get_parameter(
            "num_classes", len(self._class_names))
        num_classes = int(num_classes)
        if names_found and nc_found \
                and num_classes != len(self._class_names):
            raise ValueError(
                f"num_classes={num_classes} conflicts with "
                f"{len(self._class_names)} class_names")
        self._config = detector.DetectorConfig(
            num_classes=num_classes, width=int(width),
            score_threshold=float(threshold), max_detections=32)
        checkpoint, found = self.get_parameter("checkpoint", None)
        if found and checkpoint:
            from ..models.checkpoint import restore_pytree
            template = detector.init_params(jax.random.PRNGKey(0),
                                            self._config)
            self._params = restore_pytree(checkpoint,
                                          template={"params": template}
                                          )["params"]
        else:
            seed, _ = self.get_parameter("seed", 0)
            self._params = detector.init_params(
                jax.random.PRNGKey(int(seed)), self._config)
        self._params = self.put(self._params)
        config = self._config
        self._detect = self.jit(
            lambda params, images:
            detector.detect.__wrapped__(params, config, images))

    def process_frame(self, stream, image=None, **inputs):
        self._ensure_model()
        array = jnp.asarray(image)
        if array.dtype == jnp.uint8:
            array = array.astype(jnp.float32) / 255.0
        batched = array[None] if array.ndim == 3 else array
        result = self._detect(self._params, batched)

        boxes = np.asarray(result["boxes"][0], dtype=np.float32)
        scores = np.asarray(result["scores"][0], dtype=np.float32)
        classes = np.asarray(result["classes"][0])
        valid = np.asarray(result["valid"][0])

        rectangles, detections = [], []
        for i in np.nonzero(valid)[0]:
            x1, y1, x2, y2 = boxes[i].tolist()
            name = self._class_names[int(classes[i])] \
                if int(classes[i]) < len(self._class_names) else "?"
            # Clip to [0, 1]: ImageOverlay treats any coordinate > 1 as
            # absolute pixels, so an edge detection spilling past the
            # image border must stay in relative range.
            cx1, cy1 = min(max(x1, 0.0), 1.0), min(max(y1, 0.0), 1.0)
            cx2, cy2 = min(max(x2, 0.0), 1.0), min(max(y2, 0.0), 1.0)
            rectangles.append({
                "x": cx1, "y": cy1,
                "w": max(0.0, cx2 - cx1), "h": max(0.0, cy2 - cy1),
                "name": f"{name} {scores[i]:.2f}"})
            detections.append({"class": name,
                               "score": float(scores[i]),
                               "box": [x1, y1, x2, y2]})
        return StreamEvent.OKAY, {
            "image": image,
            "overlay": {"rectangles": rectangles},
            "detections": detections}
