"""Detection PipelineElement (BASELINE config 2; reference equivalent:
examples/yolo/yolo.py:50-93 YoloDetector wrapping ultralytics/torch).

``Detector`` hosts the framework's JAX detector (models/detector.py) on
its mesh: weights init (or restore from a checkpoint directory
parameter) at first use, forward+decode+NMS jitted once per input
resolution via the element JitCache, detections emitted as the same
overlay dict the reference's elements feed ImageOverlay
(yolo.py:80-92).
"""

from __future__ import annotations

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..models import detector
from ..pipeline import StreamEvent, TPUElement

__all__ = ["Detector"]

_DEFAULT_CLASSES = ["person", "robot_dog", "ball", "obstacle"]


class Detector(TPUElement):
    """image [H, W, 3] uint8/float -> ``overlay`` rectangles +
    ``detections`` list.

    Parameters: ``num_classes``, ``class_names``, ``score_threshold``,
    ``checkpoint`` (optional orbax directory with {"params": ...}).

    ASYNC by default: the jitted detect is dispatched from the event
    loop (JAX dispatch is asynchronous), the frame parks, and only the
    host fetch of boxes/scores blocks -- on a single fetch thread, not
    the event loop.  Frame k+1's detect is therefore already on the
    device queue while frame k's results copy back, and downstream
    stages (LLM decode) overlap detect on the device.  Set parameter
    ``synchronous: true`` for the blocking path.
    """

    is_async = True

    def __init__(self, context):
        super().__init__(context)
        self._params = None
        self._config = None
        self._detect = None
        # Single DAEMON fetch worker (not a ThreadPoolExecutor: its
        # non-daemon workers would outlive every stream and join at
        # interpreter exit).  One thread per element for the element's
        # lifetime; FIFO keeps frame completion ordered.
        self._fetch_queue: queue.Queue | None = None

    def on_replacement(self):
        super().on_replacement()
        self._params = None             # _ensure_model reloads on the
        self._detect = None             # replacement submesh
        self._stop_fetcher()            # old thread referenced old params

    def _ensure_model(self):
        if self._params is not None:
            return
        names, names_found = self.get_parameter("class_names",
                                                _DEFAULT_CLASSES)
        threshold, _ = self.get_parameter("score_threshold", 0.25)
        width, _ = self.get_parameter("width", 8)
        self._class_names = list(names)
        num_classes, nc_found = self.get_parameter(
            "num_classes", len(self._class_names))
        num_classes = int(num_classes)
        if names_found and nc_found \
                and num_classes != len(self._class_names):
            raise ValueError(
                f"num_classes={num_classes} conflicts with "
                f"{len(self._class_names)} class_names")
        self._config = detector.DetectorConfig(
            num_classes=num_classes, width=int(width),
            score_threshold=float(threshold), max_detections=32)
        checkpoint, found = self.get_parameter("checkpoint", None)
        if found and checkpoint:
            from ..models.checkpoint import restore_pytree
            template = detector.init_params(jax.random.PRNGKey(0),
                                            self._config)
            self._params = restore_pytree(checkpoint,
                                          template={"params": template}
                                          )["params"]
        else:
            seed, _ = self.get_parameter("seed", 0)
            self._params = detector.init_params(
                jax.random.PRNGKey(int(seed)), self._config)
        self._params = self.put(self._params)
        config = self._config
        self._detect = self.jit(
            lambda params, images:
            detector.detect.__wrapped__(params, config, images))

    def _dispatch(self, image):
        """Enqueue the jitted detect (asynchronous on the device)."""
        array = jnp.asarray(image)
        if array.dtype == jnp.uint8:
            array = array.astype(jnp.float32) / 255.0
        batched = array[None] if array.ndim == 3 else array
        return self._detect(self._params, batched)

    def process_frame_start(self, stream, complete, image=None, **inputs):
        self._ensure_model()
        if self._fetch_queue is None:
            self._fetch_queue = queue.Queue()
            threading.Thread(target=self._fetch_loop,
                             args=(self._fetch_queue,), daemon=True,
                             name=f"detect-fetch-{self.name}").start()
        result = self._dispatch(image)
        for leaf in jax.tree_util.tree_leaves(result):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        # Only the fetch blocks, and it blocks the fetch thread: the
        # event loop is already free to dispatch the next frame's detect.
        self._fetch_queue.put((complete, image, result))

    def _fetch_loop(self, fetch_queue):
        while True:
            item = fetch_queue.get()
            if item is None:          # drain-then-exit sentinel
                return
            self._finish_frame(*item)

    def _stop_fetcher(self):
        """Retire the fetch thread (in-flight frames drain first); a
        later async frame lazily starts a fresh one.  Without this the
        thread would pin the element -- and its device weights --
        forever."""
        fetch_queue, self._fetch_queue = self._fetch_queue, None
        if fetch_queue is not None:
            fetch_queue.put(None)

    def stop_stream(self, stream, stream_id):
        self._stop_fetcher()
        return super().stop_stream(stream, stream_id)

    def _finish_frame(self, complete, image, result):
        try:
            outputs = self._postprocess(image, result)
        except Exception as error:            # pragma: no cover - defensive
            complete(StreamEvent.ERROR, {"diagnostic": str(error)})
            return
        complete(StreamEvent.OKAY, outputs)

    def process_frame(self, stream, image=None, **inputs):
        self._ensure_model()
        result = self._dispatch(image)
        return StreamEvent.OKAY, self._postprocess(image, result)

    def _postprocess(self, image, result) -> dict:
        boxes = np.asarray(result["boxes"][0], dtype=np.float32)
        scores = np.asarray(result["scores"][0], dtype=np.float32)
        classes = np.asarray(result["classes"][0])
        valid = np.asarray(result["valid"][0])

        rectangles, detections = [], []
        for i in np.nonzero(valid)[0]:
            x1, y1, x2, y2 = boxes[i].tolist()
            name = self._class_names[int(classes[i])] \
                if int(classes[i]) < len(self._class_names) else "?"
            # Clip to [0, 1]: ImageOverlay treats any coordinate > 1 as
            # absolute pixels, so an edge detection spilling past the
            # image border must stay in relative range.
            cx1, cy1 = min(max(x1, 0.0), 1.0), min(max(y1, 0.0), 1.0)
            cx2, cy2 = min(max(x2, 0.0), 1.0), min(max(y2, 0.0), 1.0)
            rectangles.append({
                "x": cx1, "y": cy1,
                "w": max(0.0, cx2 - cx1), "h": max(0.0, cy2 - cy1),
                "name": f"{name} {scores[i]:.2f}"})
            detections.append({"class": name,
                               "score": float(scores[i]),
                               "box": [x1, y1, x2, y2]})
        return {"image": image,
                "overlay": {"rectangles": rectangles},
                "detections": detections}
