"""Detection PipelineElement (BASELINE config 2; reference equivalent:
examples/yolo/yolo.py:50-93 YoloDetector wrapping ultralytics/torch).

``Detector`` hosts the framework's JAX detector (models/detector.py) on
its mesh: weights init (or restore from a checkpoint directory
parameter) at first use, forward+decode+NMS jitted once per input
resolution via the element JitCache, detections emitted as the same
overlay dict the reference's elements feed ImageOverlay
(yolo.py:80-92).
"""

from __future__ import annotations

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..models import detector
from ..pipeline import StreamEvent, TPUElement
from ..utils import next_power_of_two

__all__ = ["Detector"]

_DEFAULT_CLASSES = ["person", "robot_dog", "ball", "obstacle"]


class Detector(TPUElement):
    """image [H, W, 3] uint8/float -> ``overlay`` rectangles +
    ``detections`` list.

    Parameters: ``num_classes``, ``class_names``, ``score_threshold``,
    ``checkpoint`` (optional orbax directory with {"params": ...}).

    ASYNC by default: each frame parks and joins a MICRO-BATCH -- all
    frames submitted in one event-loop burst (up to ``max_batch``,
    default 8) detect together as a single [N, H, W, 3] dispatch
    (batch-8 is ~14x batch-1 on v5e), flushed when the engine's mailbox
    drains so a lone frame pays no extra latency.  Batches hand off to
    the element's fetch worker thread, which dispatches (including any
    first-use jit compile) and fetches -- the event loop never blocks
    on detect device work, so frame k+1's burst collects while batch
    k runs and downstream stages (LLM decode) overlap detect on the
    device.  Set parameter ``synchronous: true`` for the blocking path.
    """

    is_async = True

    def __init__(self, context):
        super().__init__(context)
        self._params = None
        self._config = None
        self._detect = None
        # Single DAEMON fetch worker (not a ThreadPoolExecutor: its
        # non-daemon workers would outlive every stream and join at
        # interpreter exit).  One thread per element for the element's
        # lifetime; FIFO keeps frame completion ordered.
        self._fetch_queue: queue.Queue | None = None
        # Parked frames awaiting a MICRO-BATCHED dispatch: frames
        # arriving in one event-loop burst detect together as one
        # [N, H, W, 3] dispatch (batch-8 detect is ~14x batch-1 on v5e,
        # BENCH_r04 detect_batch8_fps vs detect_fps).  Flushed when
        # ``max_batch`` accumulate or when the engine's mailbox drains
        # (post_deferred), so a lone frame is never delayed.
        self._pending: list[tuple] = []
        self._flush_scheduled = False

    def on_replacement(self):
        super().on_replacement()
        self._params = None             # _ensure_model reloads on the
        self._detect = None             # replacement submesh
        self._stop_fetcher()            # old thread referenced old params

    def _ensure_model(self):
        if self._params is not None:
            return
        names, names_found = self.get_parameter("class_names",
                                                _DEFAULT_CLASSES)
        threshold, _ = self.get_parameter("score_threshold", 0.25)
        width, _ = self.get_parameter("width", 8)
        self._class_names = list(names)
        num_classes, nc_found = self.get_parameter(
            "num_classes", len(self._class_names))
        num_classes = int(num_classes)
        if names_found and nc_found \
                and num_classes != len(self._class_names):
            raise ValueError(
                f"num_classes={num_classes} conflicts with "
                f"{len(self._class_names)} class_names")
        self._config = detector.DetectorConfig(
            num_classes=num_classes, width=int(width),
            score_threshold=float(threshold), max_detections=32)
        checkpoint, found = self.get_parameter("checkpoint", None)
        if found and checkpoint:
            from ..models.checkpoint import restore_pytree
            template = detector.init_params(jax.random.PRNGKey(0),
                                            self._config)
            self._params = restore_pytree(checkpoint,
                                          template={"params": template}
                                          )["params"]
        else:
            seed, _ = self.get_parameter("seed", 0)
            self._params = detector.init_params(
                jax.random.PRNGKey(int(seed)), self._config)
        self._params = self.put(self._params)
        config = self._config
        self._detect = self.jit(
            lambda params, images:
            detector.detect.__wrapped__(params, config, images))

    @staticmethod
    def _preprocess(image):
        """image -> [H, W, 3] float32 in [0, 1]."""
        array = jnp.asarray(image)
        if array.dtype == jnp.uint8:
            array = array.astype(jnp.float32) / 255.0
        return array[0] if array.ndim == 4 else array

    def _dispatch(self, image):
        """Enqueue the jitted detect (asynchronous on the device)."""
        return self._detect(self._params, self._preprocess(image)[None])

    def process_frame_start(self, stream, complete, image=None, **inputs):
        self._ensure_model()
        if self._fetch_queue is None:
            self._fetch_queue = queue.Queue()
            threading.Thread(target=self._fetch_loop,
                             args=(self._fetch_queue,), daemon=True,
                             name=f"detect-fetch-{self.name}").start()
        max_batch, _ = self.get_parameter("max_batch", 8)
        self._pending.append((complete, image))
        if len(self._pending) >= int(max_batch):
            self._flush()
        elif not self._flush_scheduled:
            # Flush once the engine's mailboxes drain: every frame
            # submitted in this burst (frames queued behind this one,
            # frames resumed by an upstream stage this tick) joins the
            # same batched dispatch; a lone frame flushes immediately
            # after -- no timer, no added latency.  (post_deferred
            # would fire after ONE mailbox item, splitting the burst
            # into batch-1 dispatches.)
            self._flush_scheduled = True
            self.pipeline.runtime.engine.post_when_drained(
                self._flush_deferred)

    def _flush_deferred(self):
        self._flush_scheduled = False
        self._flush()

    def _flush(self):
        """Group every pending frame by (shape, dtype) -- stacking
        float16 with float32 frames would silently promote, running
        the narrower frame at a different precision than the blocking
        path -- and hand the batches to the fetch worker.  Dispatch
        (including a first-use jit compile, ~40 s through a congested
        link) happens THERE, so the event loop never blocks on detect
        device work and other stages' frames keep flowing."""
        pending, self._pending = self._pending, []
        if not pending or self._fetch_queue is None:
            for complete, image in pending:     # stopped mid-burst
                complete(StreamEvent.ERROR,
                         {"diagnostic": "detector stopped"})
            return
        by_shape: dict[tuple, list] = {}
        for complete, image in pending:
            try:
                array = self._preprocess(image)
            except Exception as error:      # malformed frame: only ITS
                complete(StreamEvent.ERROR,  # complete errors
                         {"diagnostic": f"bad image: {error}"})
                continue
            by_shape.setdefault(
                (tuple(array.shape), str(array.dtype)), []).append(
                (complete, image, array))
        if by_shape:
            # The model is SNAPSHOTTED with the batch: on_replacement
            # (mesh failure) nulls self._detect/_params on the event
            # loop while batches may still be queued -- a queued batch
            # must dispatch against the weights it was built with (or
            # fail cleanly if those weights' devices died), never
            # against a half-swapped model or a None.
            self._fetch_queue.put(
                (self._detect, self._params, list(by_shape.values())))

    def _run_batches(self, detect, params, groups):
        """Fetch-worker side of a flush: dispatch EVERY group first
        (device work pipelines across groups), then fetch and complete
        each.  A failing dispatch errors every frame of ITS group --
        anything not completed here would stay parked forever."""
        dispatched = []
        for group in groups:
            try:
                arrays = [array for _, _, array in group]
                # Pad rows repeat the first image: idempotent compute,
                # no uninitialized values, at most doubles a ragged
                # batch.
                bucket = next_power_of_two(len(arrays))
                arrays += [arrays[0]] * (bucket - len(arrays))
                result = detect(params, jnp.stack(arrays))
                for leaf in jax.tree_util.tree_leaves(result):
                    if hasattr(leaf, "copy_to_host_async"):
                        leaf.copy_to_host_async()
            except Exception as error:
                self.logger.exception("batched detect dispatch failed")
                for complete, _, _ in group:
                    complete(StreamEvent.ERROR,
                             {"diagnostic": f"detect dispatch: {error}"})
                continue
            dispatched.append((group, result))
        for group, result in dispatched:
            self._finish_batch(
                [(complete, image) for complete, image, _ in group],
                result)

    def _fetch_loop(self, fetch_queue):
        while True:
            item = fetch_queue.get()
            if item is None:          # drain-then-exit sentinel
                return
            self._run_batches(*item)

    def _stop_fetcher(self):
        """Retire the fetch thread (in-flight frames drain first); a
        later async frame lazily starts a fresh one.  Without this the
        thread would pin the element -- and its device weights --
        forever."""
        fetch_queue, self._fetch_queue = self._fetch_queue, None
        if fetch_queue is not None:
            fetch_queue.put(None)

    def stop_stream(self, stream, stream_id):
        self._flush()                   # in-flight micro-batch first
        self._stop_fetcher()
        return super().stop_stream(stream, stream_id)

    def _finish_batch(self, frames, result):
        """Fetch one batched result (a single blocking host copy for the
        whole micro-batch) and complete each frame from its row."""
        try:
            fetched = {key: np.asarray(value)
                       for key, value in result.items()}
        except Exception as error:            # pragma: no cover - defensive
            for complete, _ in frames:
                complete(StreamEvent.ERROR, {"diagnostic": str(error)})
            return
        for row, (complete, image) in enumerate(frames):
            try:
                outputs = self._postprocess(image, fetched, row)
            except Exception as error:        # pragma: no cover - defensive
                complete(StreamEvent.ERROR, {"diagnostic": str(error)})
                continue
            complete(StreamEvent.OKAY, outputs)

    def process_frame(self, stream, image=None, **inputs):
        self._ensure_model()
        result = self._dispatch(image)
        return StreamEvent.OKAY, self._postprocess(image, result)

    def _postprocess(self, image, result, row: int = 0) -> dict:
        boxes = np.asarray(result["boxes"][row], dtype=np.float32)
        scores = np.asarray(result["scores"][row], dtype=np.float32)
        classes = np.asarray(result["classes"][row])
        valid = np.asarray(result["valid"][row])

        rectangles, detections = [], []
        for i in np.nonzero(valid)[0]:
            x1, y1, x2, y2 = boxes[i].tolist()
            name = self._class_names[int(classes[i])] \
                if int(classes[i]) < len(self._class_names) else "?"
            # Clip to [0, 1]: ImageOverlay treats any coordinate > 1 as
            # absolute pixels, so an edge detection spilling past the
            # image border must stay in relative range.
            cx1, cy1 = min(max(x1, 0.0), 1.0), min(max(y1, 0.0), 1.0)
            cx2, cy2 = min(max(x2, 0.0), 1.0), min(max(y2, 0.0), 1.0)
            rectangles.append({
                "x": cx1, "y": cy1,
                "w": max(0.0, cx2 - cx1), "h": max(0.0, cy2 - cy1),
                "name": f"{name} {scores[i]:.2f}"})
            detections.append({"class": name,
                               "score": float(scores[i]),
                               "box": [x1, y1, x2, y2]})
        return {"image": image,
                "overlay": {"rectangles": rectangles},
                "detections": detections}
