"""Detection PipelineElement (BASELINE config 2; reference equivalent:
examples/yolo/yolo.py:50-93 YoloDetector wrapping ultralytics/torch).

``Detector`` hosts the framework's JAX detector (models/detector.py) on
its mesh: weights init (or restore from a checkpoint directory
parameter) at first use, forward+decode+NMS jitted once per input
resolution via the element JitCache, detections emitted as the same
overlay dict the reference's elements feed ImageOverlay
(yolo.py:80-92).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models import detector
from ..models.batching import MicroBatchElement, pad_to_bucket
from ..pipeline import StreamEvent, TPUElement

__all__ = ["Detector"]

_DEFAULT_CLASSES = ["person", "robot_dog", "ball", "obstacle"]


class Detector(MicroBatchElement, TPUElement):
    """image [H, W, 3] uint8/float -> ``overlay`` rectangles +
    ``detections`` list.

    Parameters: ``num_classes``, ``class_names``, ``score_threshold``,
    ``checkpoint`` (optional orbax directory with {"params": ...}).

    ASYNC by default: each frame parks and joins a cross-stream
    MICRO-BATCH (models/batching.py MicroBatcher) -- all frames
    submitted in one event-loop burst, from every stream, detect
    together as a single [N, H, W, 3] dispatch (batch-8 is ~14x batch-1
    on v5e), flushed when the engine's mailbox drains so a lone frame
    pays no extra latency.  Grouping keys on the PRE-UPLOAD image
    signature, so a host-side burst stacks as ONE np.stack + ONE
    host->device upload (uint8 bytes; the float conversion runs
    batched on device) instead of a per-frame upload.  Batches hand
    off to the MicroBatcher's worker thread, which dispatches
    (including any first-use jit compile) and fetches the whole result
    dict in ONE ``jax.device_get`` -- the event loop never blocks on
    detect device work, so frame k+1's burst collects while batch k
    runs and downstream stages (LLM decode) overlap detect on the
    device.  Set parameter ``synchronous: true`` for the blocking path.
    """

    is_async = True

    def __init__(self, context):
        super().__init__(context)
        self._params = None
        self._config = None
        self._detect = None

    def on_replacement(self):
        super().on_replacement()
        # Flush queued batches against the OLD weights first (they
        # dispatch against the snapshot they were built with, or fail
        # cleanly if those weights' devices died), then retire the
        # worker -- it referenced the old params.
        self.stop_microbatcher()
        self._params = None             # _ensure_model reloads on the
        self._detect = None             # replacement submesh

    def _ensure_model(self):
        if self._params is not None:
            return
        names, names_found = self.get_parameter("class_names",
                                                _DEFAULT_CLASSES)
        threshold, _ = self.get_parameter("score_threshold", 0.25)
        width, _ = self.get_parameter("width", 8)
        self._class_names = list(names)
        num_classes, nc_found = self.get_parameter(
            "num_classes", len(self._class_names))
        num_classes = int(num_classes)
        if names_found and nc_found \
                and num_classes != len(self._class_names):
            raise ValueError(
                f"num_classes={num_classes} conflicts with "
                f"{len(self._class_names)} class_names")
        self._config = detector.DetectorConfig(
            num_classes=num_classes, width=int(width),
            score_threshold=float(threshold), max_detections=32)
        checkpoint, found = self.get_parameter("checkpoint", None)
        if found and checkpoint:
            from ..models.checkpoint import restore_pytree
            template = detector.init_params(jax.random.PRNGKey(0),
                                            self._config)
            self._params = restore_pytree(checkpoint,
                                          template={"params": template}
                                          )["params"]
        else:
            seed, _ = self.get_parameter("seed", 0)
            self._params = detector.init_params(
                jax.random.PRNGKey(int(seed)), self._config)
        self._params = self.put(self._params)
        config = self._config
        self._detect = self.jit(
            lambda params, images:
            detector.detect.__wrapped__(params, config, images))

    @staticmethod
    def _preprocess(image):
        """image -> [H, W, 3] float32 in [0, 1] (device)."""
        array = jnp.asarray(image)
        if array.dtype == jnp.uint8:
            array = array.astype(jnp.float32) / 255.0
        return array[0] if array.ndim == 4 else array

    def batch_key(self, image):
        """Pre-upload grouping key: the RAW (shape, dtype) after the
        leading batch-dim squeeze, computed from host metadata alone --
        no device work at submit time.  Keying on the raw dtype keeps
        normalization per-group correct (a uint8 group divides by 255
        batched on device; a float group passes through); after
        preprocessing both land on the same compiled float32 shape, so
        splitting them costs no extra jit signature."""
        if not hasattr(image, "shape"):
            # Array-likes (nested lists) keyed via numpy metadata; the
            # worker's jnp path converts the payload itself.
            image = np.asarray(image)
        shape = tuple(image.shape)
        if len(shape) == 4:
            shape = shape[1:]
        return shape, str(image.dtype)

    def batch_context(self):
        # The model is SNAPSHOTTED with the flush: a queued batch must
        # dispatch against the weights it was built with, never a
        # half-swapped model after on_replacement.
        return self._detect, self._params

    def _dispatch(self, image):
        """Enqueue the jitted detect (asynchronous on the device)."""
        return self._detect(self._params, self._preprocess(image)[None])

    def device_fn(self, stream):
        """Fused-segment contract (with ``synchronous: true``): the
        forward+decode+NMS slate is pure device math, traced into the
        segment with the weights as captured args (never baked-in
        constants); the overlay/detections postprocess is the host
        ``finalize`` step, fed by ONE engine-counted fetch of the slate
        at the segment boundary -- which also makes a synchronous
        fused Detector legal under ``transfer_guard: disallow``."""
        from ..pipeline import DeviceFn
        self._ensure_model()
        config = self._config

        def fn(image, params):
            batch = self._preprocess(jnp.asarray(image))[None]
            return dict(detector.detect.__wrapped__(params, config,
                                                    batch))

        return DeviceFn(
            fn=fn, inputs=("image",),
            captures={"params": self._params},
            finalize=lambda fetched: self._slate_outputs(fetched, 0),
            finalize_inputs=("boxes", "scores", "classes", "valid"),
            finalize_outputs=("overlay", "detections"))

    # -- async micro-batched path ------------------------------------------

    def process_frame_start(self, stream, complete, image=None, **inputs):
        self._ensure_model()
        self.submit_microbatch(complete, image, diagnostic="bad image")

    def batch_run(self, context, key, images):
        """Worker side: stack one same-signature group and dispatch.
        An all-host group stacks ONCE on host (uint8 bytes upload raw;
        the /255 float conversion runs batched on device); groups with
        device-resident frames stack on device."""
        detect, params = context
        images = pad_to_bucket(images)
        if all(isinstance(image, np.ndarray) for image in images):
            batch = jnp.asarray(np.stack(
                [image[0] if image.ndim == 4 else image
                 for image in images]))
            if batch.dtype == jnp.uint8:
                batch = batch.astype(jnp.float32) / 255.0
        else:
            batch = jnp.stack([self._preprocess(image)
                               for image in images])
        result = detect(params, batch)
        for leaf in jax.tree_util.tree_leaves(result):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        return result

    def batch_finish(self, context, key, entries, result):
        """Fetch the batched result dict in ONE ``jax.device_get`` (the
        boxes/scores/classes/valid rows land host-side together -- a
        single blocking copy for the whole micro-batch, not four syncs
        per frame) and complete each frame from its row."""
        try:
            fetched = jax.device_get(dict(result))
        except Exception as error:            # pragma: no cover - defensive
            for complete, _ in entries:
                complete(StreamEvent.ERROR, {"diagnostic": str(error)})
            return
        for row, (complete, image) in enumerate(entries):
            try:
                outputs = self._postprocess(image, fetched, row)
            except Exception as error:        # pragma: no cover - defensive
                complete(StreamEvent.ERROR, {"diagnostic": str(error)})
                continue
            complete(StreamEvent.OKAY, outputs)

    # -- blocking path ------------------------------------------------------

    def process_frame(self, stream, image=None, **inputs):
        self._ensure_model()
        # ONE explicit host fetch of the whole result dict; the row
        # loop below then runs on host arrays with zero device syncs.
        result = jax.device_get(dict(self._dispatch(image)))
        return StreamEvent.OKAY, self._postprocess(image, result)

    def _postprocess(self, image, fetched: dict, row: int = 0) -> dict:
        return {"image": image, **self._slate_outputs(fetched, row)}

    def _slate_outputs(self, fetched: dict, row: int = 0) -> dict:
        """Build overlay/detections from the HOST-fetched result dict
        (callers did the one ``jax.device_get``; nothing here touches
        the device)."""
        boxes = np.asarray(fetched["boxes"][row], dtype=np.float32)
        scores = np.asarray(fetched["scores"][row], dtype=np.float32)
        classes = np.asarray(fetched["classes"][row])
        valid = np.asarray(fetched["valid"][row])

        rectangles, detections = [], []
        for i in np.nonzero(valid)[0]:
            x1, y1, x2, y2 = boxes[i].tolist()
            name = self._class_names[int(classes[i])] \
                if int(classes[i]) < len(self._class_names) else "?"
            # Clip to [0, 1]: ImageOverlay treats any coordinate > 1 as
            # absolute pixels, so an edge detection spilling past the
            # image border must stay in relative range.
            cx1, cy1 = min(max(x1, 0.0), 1.0), min(max(y1, 0.0), 1.0)
            cx2, cy2 = min(max(x2, 0.0), 1.0), min(max(y2, 0.0), 1.0)
            rectangles.append({
                "x": cx1, "y": cy1,
                "w": max(0.0, cx2 - cx1), "h": max(0.0, cy2 - cy1),
                "name": f"{name} {scores[i]:.2f}"})
            detections.append({"class": name,
                               "score": float(scores[i]),
                               "box": [x1, y1, x2, y2]})
        return {"overlay": {"rectangles": rectangles},
                "detections": detections}
