"""Expression elements: define/delete/rename swag values with a small
safe expression language (reference: src/aiko_services/elements/utilities/
elements.py:25-140)."""

from __future__ import annotations

import ast as python_ast
import operator

from ..pipeline import PipelineElement, StreamEvent

__all__ = ["Expression", "AllOutputs", "evaluate_expression"]

_BIN_OPS = {
    python_ast.Add: operator.add, python_ast.Sub: operator.sub,
    python_ast.Mult: operator.mul, python_ast.Div: operator.truediv,
    python_ast.FloorDiv: operator.floordiv, python_ast.Mod: operator.mod,
    python_ast.Pow: operator.pow,
}
_CMP_OPS = {
    python_ast.Eq: operator.eq, python_ast.NotEq: operator.ne,
    python_ast.Lt: operator.lt, python_ast.LtE: operator.le,
    python_ast.Gt: operator.gt, python_ast.GtE: operator.ge,
}


def evaluate_expression(text: str, variables: dict):
    """Safe arithmetic/comparison evaluator over swag variables -- no
    attribute access, no calls, no subscripts."""
    tree = python_ast.parse(str(text), mode="eval")

    def walk(node):
        if isinstance(node, python_ast.Expression):
            return walk(node.body)
        if isinstance(node, python_ast.Constant):
            return node.value
        if isinstance(node, python_ast.Name):
            if node.id in variables:
                value = variables[node.id]
                try:
                    return float(value) if isinstance(value, str) else value
                except ValueError:
                    return value
            raise NameError(node.id)
        if isinstance(node, python_ast.BinOp) \
                and type(node.op) in _BIN_OPS:
            return _BIN_OPS[type(node.op)](walk(node.left),
                                           walk(node.right))
        if isinstance(node, python_ast.UnaryOp) \
                and isinstance(node.op, python_ast.USub):
            return -walk(node.operand)
        if isinstance(node, python_ast.Compare) and len(node.ops) == 1 \
                and type(node.ops[0]) in _CMP_OPS:
            return _CMP_OPS[type(node.ops[0])](walk(node.left),
                                               walk(node.comparators[0]))
        if isinstance(node, python_ast.BoolOp):
            values = [walk(v) for v in node.values]
            return (all(values) if isinstance(node.op, python_ast.And)
                    else any(values))
        raise ValueError(f"unsupported expression node: "
                         f"{type(node).__name__}")

    return walk(tree)


class Expression(PipelineElement):
    """``expressions`` parameter: list of ``name = expr`` / ``name := expr``
    (define), ``del name`` (delete), ``new = old`` (rename via define+del
    is explicit).  Expressions see the frame's bare swag names."""

    def process_frame(self, stream, **inputs):
        expressions, found = self.get_parameter("expressions")
        if not found:
            return StreamEvent.OKAY, {}
        if isinstance(expressions, str):
            expressions = [e.strip() for e in expressions.split(";")
                           if e.strip()]
        frame = stream.frames.get(max(stream.frames)) \
            if stream.frames else None
        swag = {k: v for k, v in (frame.swag if frame else inputs).items()
                if "." not in k}
        outputs = {}
        for expression in expressions:
            try:
                if expression.startswith("del "):
                    name = expression[4:].strip()
                    if frame is not None:
                        frame.swag.pop(name, None)
                    swag.pop(name, None)
                    continue
                name, _, rhs = expression.partition("=")
                name = name.rstrip(":").strip()
                value = evaluate_expression(rhs.strip(), swag)
                swag[name] = value
                outputs[name] = value
            except Exception as error:
                return StreamEvent.ERROR, {
                    "diagnostic": f"{expression!r}: {error}"}
        return StreamEvent.OKAY, outputs


class AllOutputs(PipelineElement):
    """Emits the whole bare-name swag as outputs (reference
    utilities/elements.py:25-46)."""

    def process_frame(self, stream, **inputs):
        frame = stream.frames.get(max(stream.frames)) \
            if stream.frames else None
        swag = frame.swag if frame else inputs
        return StreamEvent.OKAY, \
            {k: v for k, v in swag.items() if "." not in k}
