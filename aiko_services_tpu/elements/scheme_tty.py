"""``tty://`` DataScheme + terminal text elements (reference:
src/aiko_services/elements/media/scheme_tty.py:26-74, text_io.py
TextReadTTY:128/TextWriteTTY:333).

Interactive terminal source/target: a background thread reads lines from
the input stream (stdin by default; injectable for tests) and a frame is
emitted per line.  ``/h`` prints input history like the reference's TTY
command history.
"""

from __future__ import annotations

import queue
import sys
import threading

from ..pipeline import DataScheme, DataSource, DataTarget, StreamEvent
from ..pipeline.stream import Stream

__all__ = ["DataSchemeTTY", "TextReadTTY", "TextWriteTTY"]


@DataScheme.register("tty")
class DataSchemeTTY(DataScheme):
    """Line-oriented terminal I/O.  The element's ``tty_input`` /
    ``tty_output`` parameters may inject file-like objects (tests, PTY
    wrappers); default stdin/stdout."""

    def __init__(self, element):
        super().__init__(element)
        self._stop = threading.Event()
        self._thread = None
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._history: list[str] = []
        self._output = None

    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        source, _ = self.element.get_parameter("tty_input", None)
        input_stream = source if source is not None else sys.stdin

        def read_loop():
            for line in input_stream:
                if self._stop.is_set():
                    break
                self._queue.put(line.rstrip("\n"))

        self._thread = threading.Thread(
            target=read_loop, daemon=True,
            name=f"tty-read-{self.element.name}")
        self._thread.start()

        def generator(stream_):
            try:
                line = self._queue.get_nowait()
            except queue.Empty:
                return StreamEvent.NO_FRAME, {}
            if line == "/h":
                for index, entry in enumerate(self._history):
                    print(f"{index}: {entry}")
                return StreamEvent.NO_FRAME, {}
            if line in ("/q", "/quit"):
                return StreamEvent.STOP, {}
            self._history.append(line)
            return StreamEvent.OKAY, {"text": line}

        self.element.create_frames(stream, frame_generator or generator,
                                   rate=rate)
        return StreamEvent.OKAY, {}

    def create_targets(self, stream: Stream, data_targets):
        target, _ = self.element.get_parameter("tty_output", None)
        self._output = target if target is not None else sys.stdout
        return StreamEvent.OKAY, {}

    def write(self, text: str):
        print(text, file=self._output, flush=True)

    def destroy_sources(self, stream: Stream):
        self._stop.set()


class TextReadTTY(DataSource):
    """One frame per line typed on the terminal (reference
    text_io.py:128-202)."""

    def process_frame(self, stream, text=None, **inputs):
        return StreamEvent.OKAY, {"text": text}


class TextWriteTTY(DataTarget):
    """Writes ``text`` lines to the terminal (reference
    text_io.py:333-356)."""

    def process_frame(self, stream, text=None, **inputs):
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeTTY):
            return StreamEvent.ERROR, {
                "diagnostic": "TextWriteTTY requires tty:// targets"}
        scheme.write(str(text))
        return StreamEvent.OKAY, {"text": text}
