"""Image elements (reference: src/aiko_services/elements/media/
image_io.py): read/resize/overlay/write with TPU-native compute.

Decode/encode is host-side (PIL); everything numeric -- resize,
normalize, overlay compositing -- runs as jax ops so image tensors stay
on device between elements (the reference does all of this on the CPU
with PIL/cv2, image_io.py:104-148,343-371).
"""

from __future__ import annotations

import numpy as np

try:
    from PIL import Image, ImageDraw
    _HAVE_PIL = True
except ImportError:                                 # pragma: no cover
    _HAVE_PIL = False

import jax
import jax.numpy as jnp

from ..models.batching import MicroBatchElement, pad_to_bucket
from ..pipeline import DataSource, DataTarget, PipelineElement, StreamEvent
from .scheme_file import DataSchemeFile

__all__ = ["ImageReadFile", "ImageWriteFile", "ImageResize",
           "ImageOverlay", "ImageOutput", "image_to_array",
           "array_to_image"]


def image_to_array(image) -> np.ndarray:
    """PIL Image -> uint8 numpy [H, W, C] (reference image_io.py:104-125
    conversion helpers)."""
    array = np.asarray(image)
    if array.ndim == 2:
        array = array[:, :, None]
    return array


def as_uint8(image) -> np.ndarray:
    """Any array-like image -> uint8 (floats treated as 0..1 and
    scaled; integer types cast).  The one conversion every image
    writer/detector backend shares."""
    array = np.asarray(image)
    if array.dtype == np.uint8:
        return array
    if array.dtype.kind == "f":
        return (np.clip(array, 0.0, 1.0) * 255).astype(np.uint8)
    return array.astype(np.uint8)


def array_to_image(array):
    """numpy/jax array [H, W, C] (uint8 or float 0..1) -> PIL Image."""
    if not _HAVE_PIL:
        raise RuntimeError("Pillow is not installed")
    array = as_uint8(array)
    if array.ndim == 3 and array.shape[-1] == 1:
        array = array[:, :, 0]
    return Image.fromarray(array)


class ImageReadFile(DataSource):
    """Reads image file(s) from ``data_sources``; emits ``image`` as a
    uint8 jax array [H, W, C] (reference image_io.py:278-307)."""

    def process_frame(self, stream, **inputs):
        path = inputs.get("path")
        if not _HAVE_PIL:
            return StreamEvent.ERROR, {"diagnostic": "Pillow missing"}
        try:
            with Image.open(path) as image:
                array = image_to_array(image.convert("RGB"))
        except OSError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return StreamEvent.OKAY, {"image": jnp.asarray(array),
                                  "path": path}


class ImageWriteFile(DataTarget):
    """Writes ``image`` to ``data_targets`` path; ``{}`` templates get the
    frame index (reference image_io.py:372-407)."""

    host_inputs = ("image",)    # sink: the engine fetches explicitly

    def process_frame(self, stream, image=None, **inputs):
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeFile):
            return StreamEvent.ERROR, {
                "diagnostic": "ImageWriteFile requires file:// targets"}
        path = scheme.target_path(stream)
        try:
            array_to_image(image).save(path)
        except (OSError, ValueError) as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return StreamEvent.OKAY, {"path": path}


class ImageResize(MicroBatchElement, PipelineElement):
    """Resize ``image`` to ``width`` x ``height`` parameters -- jax
    bilinear resize, on-device (reference image_io.py:343-371 does PIL
    resize on host).

    ASYNC by default: frames parked here -- from every stream -- resize
    together as one batched [N, H, W, C] device call (MicroBatcher;
    same admission as the Detector), and each frame's output row stays
    DEVICE-RESIDENT for the next device stage.  A host-side burst
    stacks once and uploads once.  Set ``synchronous: true`` for the
    blocking path.
    """

    is_async = True
    device_resident = True

    def __init__(self, context):
        super().__init__(context)
        # Static target size; identity scale on the leading batch dim,
        # so the same computation serves [H, W, ...] and [N, H, W, ...].
        self._resize = jax.jit(
            lambda x, h, w: jax.image.resize(
                x.astype(jnp.float32),
                x.shape[:-3] + (h, w) + x.shape[-1:]
                if x.ndim >= 3 else (h, w), method="bilinear"),
            static_argnums=(1, 2))

    def _resize_one(self, image, height: int, width: int):
        image = jnp.asarray(image)
        resized = self._resize(image, height, width)
        if image.dtype == jnp.uint8:
            resized = jnp.clip(jnp.round(resized), 0, 255) \
                .astype(jnp.uint8)
        return resized

    def process_frame(self, stream, image=None, **inputs):
        width, _ = self.get_parameter("width")
        height, _ = self.get_parameter("height")
        if not width or not height:
            return StreamEvent.ERROR, {
                "diagnostic": "ImageResize needs width/height parameters"}
        return StreamEvent.OKAY, {
            "image": self._resize_one(image, int(height), int(width))}

    def device_fn(self, stream):
        """Fused-segment contract: with ``synchronous: true`` the resize
        is a pure device computation, so a chain of device stages
        around it compiles into ONE dispatch (pipeline/fusion.py)."""
        from ..pipeline import DeviceFn
        width, _ = self.get_parameter("width")
        height, _ = self.get_parameter("height")
        if not width or not height:
            return None
        height, width = int(height), int(width)
        return DeviceFn(
            fn=lambda image: {
                "image": self._resize_one(jnp.asarray(image),
                                          height, width)},
            inputs=("image",), outputs=("image",))

    def process_frame_start(self, stream, complete, image=None, **inputs):
        self.submit_microbatch(complete, image, diagnostic="bad image")

    def batch_key(self, image):
        # Target size rides the key: streams resizing to different
        # sizes (or from different source shapes) never stack.
        width, _ = self.get_parameter("width")
        height, _ = self.get_parameter("height")
        if not width or not height:
            raise ValueError("ImageResize needs width/height parameters")
        if not hasattr(image, "shape"):     # array-likes: numpy metadata
            image = np.asarray(image)
        return (int(height), int(width), tuple(image.shape),
                str(image.dtype))

    def batch_run(self, context, key, images):
        height, width, shape, _ = key
        images = pad_to_bucket(images)
        if all(isinstance(image, np.ndarray) for image in images):
            batch = jnp.asarray(np.stack(images))   # one upload
        else:
            batch = jnp.stack([jnp.asarray(image) for image in images])
        if len(shape) == 2:             # grayscale: batch as [N, H, W, 1]
            batch = batch[..., None]
        resized = self._resize(batch, height, width)
        if batch.dtype == jnp.uint8:
            resized = jnp.clip(jnp.round(resized), 0, 255) \
                .astype(jnp.uint8)
        return resized

    def batch_finish(self, context, key, entries, result):
        if len(key[2]) == 2:
            result = result[..., 0]     # undo the grayscale channel dim
        for row, (complete, _) in enumerate(entries):
            # Row slices stay device-resident: the next device stage
            # consumes them without any host round trip.
            complete(StreamEvent.OKAY, {"image": result[row]})


class ImageOverlay(PipelineElement):
    """Draw detection overlays onto ``image``.

    ``overlay`` is ``{"rectangles": [{"x": .., "y": .., "w": .., "h": ..,
    "name": ..}], "texts": [...]}`` in relative (0..1) or absolute pixel
    coordinates (reference image_io.py:164-234 draws via PIL on host; the
    boxes here are drawn host-side too -- rectangles are tiny -- but the
    image returns as a jax array so the pipeline stays tensor-native).
    """

    # PIL drawing is host work: declare it, so the engine fetches the
    # image with ONE counted device_get instead of an implicit sync.
    host_inputs = ("image",)

    def process_frame(self, stream, image=None, overlay=None, **inputs):
        if overlay is None:
            return StreamEvent.OKAY, {"image": image}
        if not _HAVE_PIL:
            return StreamEvent.ERROR, {"diagnostic": "Pillow missing"}
        pil = array_to_image(image)
        if pil.mode != "RGB":
            pil = pil.convert("RGB")
        draw = ImageDraw.Draw(pil)
        h, w = pil.height, pil.width
        color, _ = self.get_parameter("color", "red")
        for rect in overlay.get("rectangles", []):
            x, y = float(rect["x"]), float(rect["y"])
            rw, rh = float(rect["w"]), float(rect["h"])
            if max(x, y, rw, rh) <= 1.0:        # relative coordinates
                x, y, rw, rh = x * w, y * h, rw * w, rh * h
            draw.rectangle([x, y, x + rw, y + rh], outline=color,
                           width=2)
            name = rect.get("name")
            if name:
                draw.text((x + 2, max(0, y - 12)), str(name), fill=color)
        for text in overlay.get("texts", []):
            draw.text((float(text.get("x", 4)), float(text.get("y", 4))),
                      str(text.get("text", "")), fill=color)
        return StreamEvent.OKAY, {"image": jnp.asarray(np.asarray(pil))}


class ImageOutput(PipelineElement):
    """Logs image shape/dtype; passthrough (reference
    image_io.py:149-163)."""

    def process_frame(self, stream, image=None, **inputs):
        if image is not None:
            self.logger.info("image %s %s",
                             tuple(getattr(image, "shape", ())),
                             getattr(image, "dtype", type(image)))
        return StreamEvent.OKAY, {"image": image}
