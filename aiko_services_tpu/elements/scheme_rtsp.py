"""``rtsp://`` network-video ingest AND output (reference:
src/aiko_services/elements/gstreamer/scheme_rtsp.py:27 DataSchemeRTSP,
rtsp_io.py:35 VideoReadRTSP, video_stream_writer.py:26 VideoStreamWriter
+ utilities.py:27-100 H264 codec selection -- an 843-LoC
PyGObject/GStreamer subsystem).

Ingest rides cv2's bundled FFMPEG backend (``cv2.VideoCapture`` opens
RTSP URLs directly): no GStreamer dependency, same capability --
network cameras feed the Detector.  Frames decode on the source pump
thread host-side and enter the pipeline as jax arrays; resize/normalize
run on device downstream.

Output pushes H264 over RTSP through an ffmpeg subprocess (rawvideo
RGB on stdin -> libx264 zerolatency -> ``rtsp://`` publish), the
ffmpeg-CLI equivalent of the reference's appsrc -> x264enc GStreamer
chain.

``capture_factory`` / ``writer_factory`` are injectable module hooks
(defaults: ``cv2.VideoCapture`` / the ffmpeg subprocess) so tests drive
the scheme with fakes and deployments can substitute GStreamer or a
hardware encoder without touching the elements.
"""

from __future__ import annotations

import subprocess
import threading

import numpy as np

import jax.numpy as jnp

from ..pipeline import DataScheme, DataSource, DataTarget, StreamEvent
from ..pipeline.stream import Stream
from .image import as_uint8

__all__ = ["DataSchemeRTSP", "VideoReadRTSP", "VideoWriteRTSP",
           "capture_factory", "writer_factory"]


class _CaptureGuard:
    """Serializes read() vs release(): cv2.VideoCapture is not
    thread-safe, and destroy_sources (engine thread) would otherwise
    release the handle while the pump thread sits inside read() --
    undefined behavior in native FFMPEG code.

    release() must NOT wait for an in-flight read: RTSP reads can block
    for tens of seconds (or forever) on a stalled camera, and release()
    runs on the single-threaded engine that owns every stream in the
    process.  So release() only *signals* and makes a brief attempt at
    the native release; if the pump thread is inside read(), the pump
    performs the native release itself as soon as the read returns.
    Reads after release report end-of-stream."""

    def __init__(self, capture):
        self._capture = capture
        self._lock = threading.Lock()
        self._released = threading.Event()
        self._closed = False            # native release done (under lock)

    def read(self):
        if self._released.is_set():
            self._close(blocking=True)
            return False, None
        with self._lock:
            if self._released.is_set():
                result = (False, None)
            else:
                result = self._capture.read()
        if self._released.is_set():     # released while we were reading
            self._close(blocking=True)
            return False, None
        return result

    def release(self, timeout: float = 0.5):
        """Engine-thread safe: returns within ``timeout`` even if the
        pump thread is parked inside a stalled network read."""
        self._released.set()
        self._close(timeout=timeout)

    def _close(self, blocking: bool = False, timeout: float = 0.0):
        if blocking:
            acquired = self._lock.acquire()
        else:
            acquired = self._lock.acquire(timeout=timeout) if timeout \
                else self._lock.acquire(blocking=False)
        if not acquired:
            return      # reader owns the lock; it will close afterwards
        try:
            if not self._closed:
                self._closed = True
                release = getattr(self._capture, "release", None)
                if release is not None:
                    release()
        finally:
            self._lock.release()


def _default_capture_factory(url: str):
    try:
        import cv2
    except ImportError as error:                    # pragma: no cover
        raise RuntimeError("rtsp:// needs cv2 (or an injected "
                           "capture_factory)") from error
    return cv2.VideoCapture(url)


capture_factory = _default_capture_factory


class _FfmpegWriter:
    """H264/RTSP publisher: raw RGB frames on an ffmpeg subprocess's
    stdin, x264 zerolatency encode, RTSP push to the URL (an RTSP
    server -- e.g. mediamtx -- must be listening there, the same
    contract as the reference's udpsink/rtmpsink targets)."""

    def __init__(self, url: str, width: int, height: int, fps: float):
        self._process = subprocess.Popen(
            ["ffmpeg", "-loglevel", "error", "-f", "rawvideo",
             "-pix_fmt", "rgb24", "-s", f"{width}x{height}",
             "-r", str(fps), "-i", "-",
             "-c:v", "libx264", "-preset", "ultrafast",
             "-tune", "zerolatency", "-pix_fmt", "yuv420p",
             "-f", "rtsp", url],
            stdin=subprocess.PIPE)

    def write(self, rgb_frame: np.ndarray):
        self._process.stdin.write(
            np.ascontiguousarray(rgb_frame, dtype=np.uint8).tobytes())

    def close(self):
        try:
            self._process.stdin.close()
        except (BrokenPipeError, OSError, ValueError):
            pass                    # encoder already gone / double close
        try:
            self._process.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            # ffmpeg wedged pushing to an unreachable server: a leaked
            # encoder per stream restart otherwise.
            self.kill()

    def kill(self):
        """Hard-stop the encoder (idempotent, any-thread safe: Popen
        ops take internal locks).  A kill also unblocks a pump thread
        stuck in write() -- the pipe breaks, the thread drains out."""
        self._process.kill()
        self._process.wait()


def _default_writer_factory(url: str, width: int, height: int,
                            fps: float):
    return _FfmpegWriter(url, width, height, fps)


writer_factory = _default_writer_factory


@DataScheme.register("rtsp")
class DataSchemeRTSP(DataScheme):
    """Opens the stream URL and pumps decoded frames as ``image``s."""

    @property
    def _key(self) -> str:
        # Per-element key: two rtsp sources in one stream must not
        # clobber each other's handle (pattern of video.py's counters).
        return f"{self.element.name}.rtsp_capture"

    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        if len(data_sources) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"rtsp:// takes exactly one URL per "
                              f"element, got {len(data_sources)}"}
        url = data_sources[0]                       # full rtsp:// URL
        try:
            capture = capture_factory(url)
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"rtsp open failed: {error}"}
        opened = getattr(capture, "isOpened", lambda: True)()
        if not opened:
            release = getattr(capture, "release", None)
            if release is not None:     # free the native FFMPEG context
                release()
            return StreamEvent.ERROR, {
                "diagnostic": f"cannot open rtsp stream {url}"}
        stream.variables[self._key] = _CaptureGuard(capture)
        generator = frame_generator or self._frame_generator
        self.element.create_frames(stream, generator, rate=rate)
        return StreamEvent.OKAY, {}

    def _frame_generator(self, stream: Stream):
        guard = stream.variables.get(self._key)
        if guard is None:
            return StreamEvent.STOP, {}
        okay, frame = guard.read()
        if not okay:
            # Network cameras drop out; stop the stream gracefully so a
            # supervisor (lifecycle manager) can restart it.
            return StreamEvent.STOP, {}
        array = np.asarray(frame)
        if array.ndim == 3 and array.shape[2] == 3:
            array = array[:, :, ::-1]               # BGR -> RGB
        return StreamEvent.OKAY, {"image": jnp.asarray(array)}

    def destroy_sources(self, stream: Stream):
        guard = stream.variables.pop(self._key, None)
        if guard is not None:
            guard.release()

    # -- output side (reference video_stream_writer.py:26) ----------------

    @property
    def _target_key(self) -> str:
        return f"{self.element.name}.rtsp_writer"

    def create_targets(self, stream: Stream, data_targets):
        if len(data_targets) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"rtsp:// takes exactly one target URL "
                              f"per element, got {len(data_targets)}"}
        # The writer needs the frame geometry, so it opens lazily on
        # the first written frame; stash the URL for write().
        stream.variables[self._target_key + ".url"] = data_targets[0]
        return StreamEvent.OKAY, {}

    def write(self, stream: Stream, image, fps: float = 30.0) -> bool:
        """Publish one frame (any array-like HxWx3 RGB).  Returns False
        when the frame was dropped because the encoder is behind (video
        drop semantics -- a stalled RTSP server must never stall the
        engine thread, the same contract _CaptureGuard keeps on the
        ingest side; the pump thread absorbs the blocking pipe write).
        Raises ValueError on a mid-stream geometry change: the encoder
        is told the frame size once, and a different byte count would
        silently misframe every later frame into garbage."""
        from .audio_live import _PlaybackPump

        frame = as_uint8(image)
        pump = stream.variables.get(self._target_key)
        if pump is None:
            url = stream.variables[self._target_key + ".url"]
            writer = writer_factory(url, frame.shape[1], frame.shape[0],
                                    fps)
            pump = _PlaybackPump(writer, queue_depth=30, label="rtsp")
            stream.variables[self._target_key] = pump
            stream.variables[self._target_key + ".shape"] = frame.shape
        expected = stream.variables[self._target_key + ".shape"]
        if frame.shape != expected:
            raise ValueError(
                f"rtsp frame geometry changed mid-stream: "
                f"{frame.shape} vs encoder's {expected}")
        return pump.try_write(frame)

    def destroy_targets(self, stream: Stream):
        stream.variables.pop(self._target_key + ".url", None)
        stream.variables.pop(self._target_key + ".shape", None)
        pump = stream.variables.pop(self._target_key, None)
        if pump is not None and not pump.close():
            # Pump thread wedged inside a stalled pipe write: the
            # encoder must be hard-stopped or it leaks per restart
            # (the kill breaks the pipe, which also frees the thread).
            kill = getattr(pump.backend, "kill", None)
            if kill is not None:
                kill()


class VideoWriteRTSP(DataTarget):
    """H264/RTSP output DataTarget: ``data_targets: rtsp://host/path``;
    publishes each frame's ``image`` to the stream URL and passes it
    through (reference video_stream_writer.py:26 VideoStreamWriter /
    video_io's VideoWriteFile shape).  Parameter ``rate`` sets the
    encoder's nominal fps (default 30)."""

    def process_frame(self, stream: Stream, image=None, **inputs):
        scheme = self.scheme_for(stream)
        if scheme is None or image is None:
            return StreamEvent.ERROR, {
                "diagnostic": "rtsp target not initialized or no image"}
        rate, _ = self.get_parameter("rate", 30.0)
        try:
            written = scheme.write(stream, image, fps=float(rate))
        except (OSError, ValueError, RuntimeError) as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"rtsp publish failed: {error}"}
        if not written:
            self.logger.warning("rtsp encoder behind; frame dropped")
        return StreamEvent.OKAY, {"image": image, **inputs}


class VideoReadRTSP(DataSource):
    """Network camera DataSource: ``data_sources: rtsp://host/path``;
    emits ``image`` per decoded frame (reference rtsp_io.py:35)."""
