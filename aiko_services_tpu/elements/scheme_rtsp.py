"""``rtsp://`` network-camera ingest (reference:
src/aiko_services/elements/gstreamer/scheme_rtsp.py:27 DataSchemeRTSP,
rtsp_io.py:35 VideoReadRTSP -- an 843-LoC PyGObject/GStreamer subsystem).

Here decode rides cv2's bundled FFMPEG backend (``cv2.VideoCapture``
opens RTSP URLs directly): no GStreamer dependency, same capability --
network cameras feed the Detector.  Frames decode on the source pump
thread host-side and enter the pipeline as jax arrays; resize/normalize
run on device downstream.

``capture_factory`` is an injectable module hook (default
``cv2.VideoCapture``) so tests drive the scheme with fake captures and
deployments can substitute a GStreamer/ffmpeg-subprocess reader without
touching the element.
"""

from __future__ import annotations

import threading

import numpy as np

import jax.numpy as jnp

from ..pipeline import DataScheme, DataSource, StreamEvent
from ..pipeline.stream import Stream

__all__ = ["DataSchemeRTSP", "VideoReadRTSP", "capture_factory"]


class _CaptureGuard:
    """Serializes read() vs release(): cv2.VideoCapture is not
    thread-safe, and destroy_sources (engine thread) would otherwise
    release the handle while the pump thread sits inside read() --
    undefined behavior in native FFMPEG code.

    release() must NOT wait for an in-flight read: RTSP reads can block
    for tens of seconds (or forever) on a stalled camera, and release()
    runs on the single-threaded engine that owns every stream in the
    process.  So release() only *signals* and makes a brief attempt at
    the native release; if the pump thread is inside read(), the pump
    performs the native release itself as soon as the read returns.
    Reads after release report end-of-stream."""

    def __init__(self, capture):
        self._capture = capture
        self._lock = threading.Lock()
        self._released = threading.Event()
        self._closed = False            # native release done (under lock)

    def read(self):
        if self._released.is_set():
            self._close(blocking=True)
            return False, None
        with self._lock:
            if self._released.is_set():
                result = (False, None)
            else:
                result = self._capture.read()
        if self._released.is_set():     # released while we were reading
            self._close(blocking=True)
            return False, None
        return result

    def release(self, timeout: float = 0.5):
        """Engine-thread safe: returns within ``timeout`` even if the
        pump thread is parked inside a stalled network read."""
        self._released.set()
        self._close(timeout=timeout)

    def _close(self, blocking: bool = False, timeout: float = 0.0):
        if blocking:
            acquired = self._lock.acquire()
        else:
            acquired = self._lock.acquire(timeout=timeout) if timeout \
                else self._lock.acquire(blocking=False)
        if not acquired:
            return      # reader owns the lock; it will close afterwards
        try:
            if not self._closed:
                self._closed = True
                release = getattr(self._capture, "release", None)
                if release is not None:
                    release()
        finally:
            self._lock.release()


def _default_capture_factory(url: str):
    try:
        import cv2
    except ImportError as error:                    # pragma: no cover
        raise RuntimeError("rtsp:// needs cv2 (or an injected "
                           "capture_factory)") from error
    return cv2.VideoCapture(url)


capture_factory = _default_capture_factory


@DataScheme.register("rtsp")
class DataSchemeRTSP(DataScheme):
    """Opens the stream URL and pumps decoded frames as ``image``s."""

    @property
    def _key(self) -> str:
        # Per-element key: two rtsp sources in one stream must not
        # clobber each other's handle (pattern of video.py's counters).
        return f"{self.element.name}.rtsp_capture"

    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        if len(data_sources) != 1:
            return StreamEvent.ERROR, {
                "diagnostic": f"rtsp:// takes exactly one URL per "
                              f"element, got {len(data_sources)}"}
        url = data_sources[0]                       # full rtsp:// URL
        try:
            capture = capture_factory(url)
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"rtsp open failed: {error}"}
        opened = getattr(capture, "isOpened", lambda: True)()
        if not opened:
            release = getattr(capture, "release", None)
            if release is not None:     # free the native FFMPEG context
                release()
            return StreamEvent.ERROR, {
                "diagnostic": f"cannot open rtsp stream {url}"}
        stream.variables[self._key] = _CaptureGuard(capture)
        generator = frame_generator or self._frame_generator
        self.element.create_frames(stream, generator, rate=rate)
        return StreamEvent.OKAY, {}

    def _frame_generator(self, stream: Stream):
        guard = stream.variables.get(self._key)
        if guard is None:
            return StreamEvent.STOP, {}
        okay, frame = guard.read()
        if not okay:
            # Network cameras drop out; stop the stream gracefully so a
            # supervisor (lifecycle manager) can restart it.
            return StreamEvent.STOP, {}
        array = np.asarray(frame)
        if array.ndim == 3 and array.shape[2] == 3:
            array = array[:, :, ::-1]               # BGR -> RGB
        return StreamEvent.OKAY, {"image": jnp.asarray(array)}

    def destroy_sources(self, stream: Stream):
        guard = stream.variables.pop(self._key, None)
        if guard is not None:
            guard.release()


class VideoReadRTSP(DataSource):
    """Network camera DataSource: ``data_sources: rtsp://host/path``;
    emits ``image`` per decoded frame (reference rtsp_io.py:35)."""
