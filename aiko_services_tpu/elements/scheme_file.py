"""``file://`` DataScheme (reference: src/aiko_services/elements/media/
scheme_file.py:25-107): glob templating with ``{}``, batch frame
generation, single-file fast path."""

from __future__ import annotations

import glob
import os

from ..pipeline import DataScheme, StreamEvent
from ..pipeline.stream import Stream

__all__ = ["DataSchemeFile"]


@DataScheme.register("file")
class DataSchemeFile(DataScheme):
    def create_sources(self, stream: Stream, data_sources,
                       frame_generator=None, rate=None):
        paths: list[str] = []
        for url in data_sources:
            path = DataScheme.parse_data_url_path(url)
            if "{}" in path or "*" in path:
                pattern = path.replace("{}", "*")
                paths.extend(sorted(glob.glob(pattern)))
            else:
                paths.append(path)
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            return StreamEvent.ERROR, {
                "diagnostic": f"missing files: {missing}"}
        stream.variables["source_paths"] = paths
        stream.variables["source_index"] = 0

        if len(paths) == 1 and frame_generator is None:
            self.element.create_frame(stream, {"path": paths[0]})
            return StreamEvent.OKAY, {}

        def path_generator(stream_):
            index = stream_.variables["source_index"]
            if index >= len(stream_.variables["source_paths"]):
                return StreamEvent.STOP, {}
            stream_.variables["source_index"] = index + 1
            return (StreamEvent.OKAY,
                    {"path": stream_.variables["source_paths"][index]})

        generator = frame_generator or path_generator
        self.element.create_frames(stream, generator, rate=rate)
        return StreamEvent.OKAY, {}

    def create_targets(self, stream: Stream, data_targets):
        path = DataScheme.parse_data_url_path(data_targets[0])
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        stream.variables["target_path"] = path
        stream.variables["target_index"] = 0
        return StreamEvent.OKAY, {}

    def target_path(self, stream: Stream) -> str:
        """Next output path; ``{}`` templates get the frame index."""
        path = stream.variables["target_path"]
        if "{}" in path:
            index = stream.variables["target_index"]
            stream.variables["target_index"] = index + 1
            return path.replace("{}", str(index))
        return path
