"""Speech pipeline elements: ASR (speech-to-text) and TTS
(text-to-speech), hosting the framework's own JAX models in HBM
(BASELINE config 5; reference equivalents:
examples/speech/speech_elements.py PE_WhisperX at :203-239 wrapping the
external whisperx/CUDA model, PE_COQUI_TTS at :122-146 wrapping Coqui
VITS -- here both models are the framework's, models/asr.py and
models/tts.py).

Both elements resolve a ``checkpoint`` parameter (orbax directory, the
same contract as the LLM/Detector elements) for fitted weights; without
one they run from random init, which exercises every shape/compile path
(the architecture is the deliverable -- see models/asr.py docstring).

Audio longer than one ASR chunk is split into chunk-sized rows and
transcribed as ONE batch: a single device dispatch, one compiled
program, however long the utterance (the ShapeBucketer stance --
never a data-dependent shape, always a padded batch).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..models import asr as asr_model
from ..models import tts as tts_model
from ..models.checkpoint import maybe_restore
from ..pipeline import PipelineElement, StreamEvent
from ..pipeline.tensor import ShapeBucketer

__all__ = ["ASR", "TTS"]


def _chunk_rows(samples: np.ndarray, chunk: int,
                bucketer: ShapeBucketer) -> np.ndarray:
    """Mono waveform [N] -> [bucket(ceil(N/chunk)), chunk], zero
    right-padded.  The row count is bucketed (powers of two from 1) so
    ``transcribe`` compiles once per bucket, not once per utterance
    length."""
    samples = np.asarray(samples, dtype=np.float32).reshape(-1)
    n_rows = bucketer.bucket(max(1, -(-len(samples) // chunk)))
    rows = np.zeros((n_rows, chunk), dtype=np.float32)
    flat = samples[: n_rows * chunk]
    rows.reshape(-1)[: len(flat)] = flat
    return rows


class ASR(PipelineElement):
    """``audio`` [N] or [N, C] + ``sample_rate`` -> transcript ``text``.

    Parameters: ``checkpoint`` (orbax dir of fitted AsrConfig weights),
    ``model_size`` (``tiny``/``base``), ``sample_rate`` (model rate,
    default 16000), ``streaming`` (true: incremental live mode -- each
    frame's audio feeds a per-stream :class:`StreamingAsr`, the frame
    emits whatever text completed chunks produced, and ``stop_stream``
    flushes the tail; the ``mic://`` -> ASR live path).  Input audio at
    another rate should pass through
    :class:`~aiko_services_tpu.elements.audio.AudioResampler` first
    (same contract as the reference's resampler -> whisper chain).
    """

    host_inputs = ("audio",)    # np.asarray front door: one counted fetch

    _SIZES = {"tiny": asr_model.AsrConfig.tiny,
              "base": asr_model.AsrConfig.base}

    def __init__(self, context):
        super().__init__(context)
        self._params = None
        self._config = None
        self._bucketer = ShapeBucketer(minimum=1)
        self._streamers: dict = {}

    def _ensure_model(self):
        if self._params is not None:
            return
        size, _ = self.get_parameter("model_size", "tiny")
        if str(size) not in self._SIZES:
            raise ValueError(f"ASR model_size {size!r}: expected one of "
                             f"{sorted(self._SIZES)}")
        self._config = self._SIZES[str(size)]()
        seed, _ = self.get_parameter("seed", 0)
        checkpoint, _ = self.get_parameter("checkpoint", None)
        self._params = maybe_restore(
            asr_model.init_params(jax.random.PRNGKey(int(seed)),
                                  self._config),
            checkpoint)

    def _streaming(self) -> bool:
        from ..utils import parse_bool
        return parse_bool(self.get_parameter("streaming", False)[0])

    def process_frame(self, stream, audio=None, sample_rate=16000,
                      **inputs):
        try:
            self._ensure_model()
        except ValueError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        config = self._config
        if int(sample_rate) != config.sample_rate:
            return StreamEvent.ERROR, {
                "diagnostic": f"ASR expects {config.sample_rate} Hz audio"
                              f", got {sample_rate} (add AudioResampler)"}
        samples = np.asarray(audio, dtype=np.float32)
        if samples.ndim == 2:                      # [N, C] -> mono
            samples = samples.mean(axis=-1)
        if self._streaming():
            streamer = self._streamers.get(stream.stream_id)
            if streamer is None:
                # hop_seconds: sub-chunk live hypothesis every hop;
                # endpoint_silence: trailing quiet finalizes the
                # utterance early (models/asr.py StreamingAsr).
                hop, _ = self.get_parameter("hop_seconds", None)
                endpoint, _ = self.get_parameter("endpoint_silence",
                                                 None)
                streamer = asr_model.StreamingAsr(
                    self._params, config,
                    hop_seconds=float(hop) if hop else None,
                    endpoint_silence=float(endpoint) if endpoint
                    else None)
                self._streamers[stream.stream_id] = streamer
            finalized_before = streamer.chunks_transcribed
            text = streamer.push(samples)
            # utterance_end marks the EVENT (a chunk filled or the
            # endpoint fired), independent of whether the decoded text
            # is empty -- downstream gates (TextFilter gate:
            # utterance_end) trigger on utterance boundaries, not on
            # what the model happened to emit.
            return StreamEvent.OKAY, {
                "text": text, "partial_text": streamer.partial_text,
                "stable_text": streamer.stable_text,
                "utterance_end":
                    streamer.chunks_transcribed > finalized_before}
        chunk = int(config.sample_rate * config.chunk_seconds)
        true_rows = max(1, -(-len(samples) // chunk))
        rows = _chunk_rows(samples, chunk, self._bucketer)
        tokens = asr_model.transcribe(self._params, config,
                                      jnp.asarray(rows))
        # Decode only the real chunks -- bucket-padding rows are pure
        # silence and a fitted model may still hallucinate tokens there.
        text = "".join(asr_model.decode_text(config, row)
                       for row in np.asarray(tokens)[:true_rows])
        return StreamEvent.OKAY, {"text": text}

    def stop_stream(self, stream, stream_id):
        streamer = self._streamers.pop(stream_id, None)
        if streamer is not None:
            tail = streamer.flush()
            if tail:
                # The stream is closing; surface the tail on the share
                # so callers (and tests) can retrieve it.
                self.pipeline.share[f"asr_tail_{stream_id}"] = tail


class TTS(PipelineElement):
    """``text`` -> ``audio`` waveform [N] + ``sample_rate``.

    Parameters: ``checkpoint`` (orbax dir of fitted TtsConfig weights),
    ``model_size`` (``tiny``/``base``), ``seed``.
    """

    _SIZES = {"tiny": tts_model.TtsConfig.tiny, "base": tts_model.TtsConfig}

    def __init__(self, context):
        super().__init__(context)
        self._params = None
        self._config = None

    def _ensure_model(self):
        if self._params is not None:
            return
        size, _ = self.get_parameter("model_size", "tiny")
        if str(size) not in self._SIZES:
            raise ValueError(f"TTS model_size {size!r}: expected one of "
                             f"{sorted(self._SIZES)}")
        self._config = self._SIZES[str(size)]()
        seed, _ = self.get_parameter("seed", 0)
        checkpoint, _ = self.get_parameter("checkpoint", None)
        self._params = maybe_restore(
            tts_model.init_params(jax.random.PRNGKey(int(seed)),
                                  self._config),
            checkpoint)

    def process_frame(self, stream, text=None, **inputs):
        if text is None:
            return StreamEvent.ERROR, {
                "diagnostic": "TTS frame has no 'text' input"}
        try:
            self._ensure_model()
        except ValueError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        waveform = tts_model.synthesize(self._params, self._config,
                                        str(text))
        return StreamEvent.OKAY, {
            "audio": jnp.asarray(waveform),
            "sample_rate": self._config.sample_rate}
