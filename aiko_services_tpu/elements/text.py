"""Text I/O elements (reference: src/aiko_services/elements/media/
text_io.py): file read/write, sampling, case transforms.  TTY/socket
variants live with the interactive tooling."""

from __future__ import annotations

import os

from ..pipeline import (DataSource, DataTarget, PipelineElement,
                        StreamEvent)
from .scheme_file import DataSchemeFile

__all__ = ["TextReadFile", "TextWriteFile", "TextTransform", "TextSample",
           "TextFilter", "TextOutput"]


class TextReadFile(DataSource):
    """Reads text file(s) named by ``data_sources``; emits one frame per
    file with ``text`` (reference text_io.py:107-128)."""

    def process_frame(self, stream, **inputs):
        path = inputs.get("path")
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return StreamEvent.OKAY, {"text": text, "path": path}


class TextWriteFile(DataTarget):
    """Writes ``text`` to the ``data_targets`` path; ``{}`` templates get
    the frame index (reference text_io.py:280-333)."""

    def process_frame(self, stream, text=None, **inputs):
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeFile):
            return StreamEvent.ERROR, {
                "diagnostic": "TextWriteFile requires file:// targets"}
        path = scheme.target_path(stream)
        try:
            with open(path, "a" if "{}" not in
                      stream.variables["target_path"] else "w") as fh:
                fh.write(str(text))
                if not str(text).endswith(os.linesep):
                    fh.write(os.linesep)
        except OSError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return StreamEvent.OKAY, {"path": path}


class TextTransform(PipelineElement):
    """Case/strip transforms chosen by the ``transform`` parameter
    (reference text_io.py:236-280)."""

    TRANSFORMS = {
        "lower": str.lower, "upper": str.upper, "title": str.title,
        "strip": str.strip, "none": lambda t: t,
    }

    def process_frame(self, stream, text=None, **inputs):
        name, _ = self.get_parameter("transform", "none")
        transform = self.TRANSFORMS.get(str(name))
        if transform is None:
            return StreamEvent.ERROR, {
                "diagnostic": f"unknown transform {name!r}"}
        return StreamEvent.OKAY, {"text": transform(str(text))}


class TextFilter(PipelineElement):
    """Gates frames on content: drops frames whose ``text`` is empty or
    whitespace, or -- with parameter ``gate`` naming another input --
    frames where THAT input is falsy.  The streaming-speech use:
    ``gate: utterance_end`` passes only the frames where the ASR
    finalized an utterance, so per-hop partial frames never reach a
    downstream LLM stage (the reference's speech pipelines likewise act
    on whisper's completed segments, speech_elements.py:53-84)."""

    @staticmethod
    def _truthy(value) -> bool:
        if value is None:
            return False
        if isinstance(value, str):
            return bool(value.strip())
        ndim = getattr(value, "ndim", None)     # numpy/jax values
        if ndim is not None:
            if ndim == 0:                       # scalar (np.bool_(False),
                return bool(value)              # np.int64(0), ...)
            return int(value.size) > 0          # real arrays: non-empty
        return bool(value)

    def process_frame(self, stream, text=None, **inputs):
        gate, found = self.get_parameter("gate", None)
        if found and gate:
            # 'text' binds to the named parameter, never **inputs.
            if str(gate) == "text":
                value = text
            elif str(gate) in inputs:
                value = inputs[str(gate)]
            else:
                # A typo'd/unwired gate must surface, not silently
                # drop every frame forever.
                return StreamEvent.ERROR, {
                    "diagnostic": f"TextFilter gate {gate!r} is not an "
                                  f"input of this frame "
                                  f"(inputs: {sorted(inputs)})"}
        else:
            value = text
        if not self._truthy(value):
            return StreamEvent.DROP_FRAME, {}
        return StreamEvent.OKAY, {"text": text}


class TextSample(PipelineElement):
    """Passes every Nth frame, drops the rest (reference
    text_io.py:220-236)."""

    def start_stream(self, stream, stream_id):
        stream.variables[f"{self.name}.count"] = 0
        return StreamEvent.OKAY, {}

    def process_frame(self, stream, text=None, **inputs):
        rate, _ = self.get_parameter("sample_rate", 1)
        count = stream.variables.get(f"{self.name}.count", 0)
        stream.variables[f"{self.name}.count"] = count + 1
        if count % int(rate):
            return StreamEvent.DROP_FRAME, {}
        return StreamEvent.OKAY, {"text": text}


class TextOutput(PipelineElement):
    """Collects text into ``pipeline.share`` and optionally prints --
    tail element for tests/demos (reference text_io.py:89-107)."""

    def process_frame(self, stream, text=None, **inputs):
        collected = stream.variables.setdefault("text_output", [])
        collected.append(text)
        if self.get_parameter("print", False)[0]:
            print(text)
        return StreamEvent.OKAY, {"text": text}
