"""Video elements (reference: src/aiko_services/elements/media/
video_io.py): cv2 VideoCapture/VideoWriter streaming, frame sampling,
plus the webcam source (webcam_io.py:75).

Decode stays host-side (cv2); decoded frames enter the pipeline as jax
arrays so downstream elements (resize, detect) run on device.
"""

from __future__ import annotations

import numpy as np

try:
    import cv2
    _HAVE_CV2 = True
except ImportError:                                 # pragma: no cover
    _HAVE_CV2 = False

import jax.numpy as jnp

from ..pipeline import DataSource, DataTarget, PipelineElement, StreamEvent
from .image import as_uint8 as _as_uint8
from .scheme_file import DataSchemeFile

__all__ = ["VideoReadFile", "VideoWriteFile", "VideoSample",
           "VideoOutput", "VideoReadWebcam"]


class VideoReadFile(DataSource):
    """Streams frames from video file(s): one pipeline frame per video
    frame, emitted by a rate-capped generator (reference
    video_io.py:129-198)."""

    def start_stream(self, stream, stream_id):
        if not _HAVE_CV2:
            return StreamEvent.ERROR, {"diagnostic": "cv2 missing"}
        return super().start_stream(stream, stream_id)

    def frame_generator(self, stream):
        capture = stream.variables.get("video_capture")
        if capture is None:
            paths = stream.variables.get("source_paths", [])
            index = stream.variables.get("video_path_index", 0)
            if index >= len(paths):
                return StreamEvent.STOP, {}
            capture = cv2.VideoCapture(paths[index])
            if not capture.isOpened():
                return StreamEvent.ERROR, {
                    "diagnostic": f"cannot open {paths[index]}"}
            stream.variables["video_capture"] = capture
            stream.variables["video_path_index"] = index + 1
        okay, frame = capture.read()
        if not okay:
            capture.release()
            stream.variables["video_capture"] = None
            return self.frame_generator(stream)     # next file or STOP
        rgb = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        return StreamEvent.OKAY, {"image": jnp.asarray(rgb)}

    def stop_stream(self, stream, stream_id):
        capture = stream.variables.pop("video_capture", None)
        if capture is not None:
            capture.release()
        return super().stop_stream(stream, stream_id)


class VideoWriteFile(DataTarget):
    """Writes ``image`` frames to a video file (reference
    video_io.py:263-337).  Writer opens lazily on the first frame (codec
    from the ``codec`` parameter, default MJPG; rate from ``rate``)."""

    host_inputs = ("image",)    # sink: the engine fetches explicitly

    def process_frame(self, stream, image=None, **inputs):
        if not _HAVE_CV2:
            return StreamEvent.ERROR, {"diagnostic": "cv2 missing"}
        scheme = self.scheme_for(stream)
        if not isinstance(scheme, DataSchemeFile):
            return StreamEvent.ERROR, {
                "diagnostic": "VideoWriteFile requires file:// targets"}
        writer = stream.variables.get("video_writer")
        array = _as_uint8(image)
        if writer is None:
            path = scheme.target_path(stream)
            codec, _ = self.get_parameter("codec", "MJPG")
            rate, _ = self.get_parameter("rate", 30.0)
            fourcc = cv2.VideoWriter_fourcc(*str(codec))
            writer = cv2.VideoWriter(
                path, fourcc, float(rate),
                (array.shape[1], array.shape[0]))
            if not writer.isOpened():
                return StreamEvent.ERROR, {
                    "diagnostic": f"cannot open writer for {path}"}
            stream.variables["video_writer"] = writer
            stream.variables["video_writer_path"] = path
        writer.write(cv2.cvtColor(array, cv2.COLOR_RGB2BGR))
        return StreamEvent.OKAY, {
            "path": stream.variables["video_writer_path"]}

    def stop_stream(self, stream, stream_id):
        writer = stream.variables.pop("video_writer", None)
        if writer is not None:
            writer.release()
        return super().stop_stream(stream, stream_id)


class VideoSample(PipelineElement):
    """Passes every Nth frame (``sample_rate``), drops the rest
    (reference video_io.py:198-215)."""

    def start_stream(self, stream, stream_id):
        stream.variables[f"{self.name}.count"] = 0
        return StreamEvent.OKAY, {}

    def process_frame(self, stream, image=None, **inputs):
        rate, _ = self.get_parameter("sample_rate", 1)
        key = f"{self.name}.count"
        count = stream.variables.get(key, 0)
        stream.variables[key] = count + 1
        if int(rate) > 1 and count % int(rate):
            return StreamEvent.DROP_FRAME, {}
        return StreamEvent.OKAY, {"image": image}


class VideoOutput(PipelineElement):
    """Logs frame shape; passthrough (reference video_io.py:111-129)."""

    def process_frame(self, stream, image=None, **inputs):
        if image is not None:
            self.logger.info("video frame %s",
                             tuple(getattr(image, "shape", ())))
        return StreamEvent.OKAY, {"image": image}


class VideoReadWebcam(DataSource):
    """Webcam DataSource (reference webcam_io.py:75): ``webcam://<index>``
    via cv2.VideoCapture(index)."""

    def start_stream(self, stream, stream_id):
        if not _HAVE_CV2:
            return StreamEvent.ERROR, {"diagnostic": "cv2 missing"}
        source, _ = self.get_parameter("data_sources", "webcam://0")
        url = source[0] if isinstance(source, list) else source
        index = int(str(url).rsplit("://", 1)[-1] or 0)
        capture = cv2.VideoCapture(index)
        if not capture.isOpened():
            return StreamEvent.ERROR, {
                "diagnostic": f"cannot open webcam {index}"}
        stream.variables["webcam_capture"] = capture
        rate, _ = self.get_parameter("rate", None)
        self.create_frames(stream, self.frame_generator,
                           rate=float(rate) if rate else None)
        return StreamEvent.OKAY, {}

    def frame_generator(self, stream):
        capture = stream.variables.get("webcam_capture")
        if capture is None:
            return StreamEvent.STOP, {}
        okay, frame = capture.read()
        if not okay:
            return StreamEvent.ERROR, {"diagnostic": "webcam read failed"}
        rgb = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        return StreamEvent.OKAY, {"image": jnp.asarray(rgb)}

    def stop_stream(self, stream, stream_id):
        capture = stream.variables.pop("webcam_capture", None)
        if capture is not None:
            capture.release()
        return StreamEvent.OKAY, {}
