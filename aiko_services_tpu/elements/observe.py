"""Observability elements (reference: src/aiko_services/elements/observe/
elements.py): Inspect dumps selected swag values; Metrics reports
per-element times from frame.metrics."""

from __future__ import annotations

from ..pipeline import PipelineElement, StreamEvent

__all__ = ["Inspect", "Metrics"]


class Inspect(PipelineElement):
    """Dumps chosen swag values to log/print/file per the ``inspect``
    parameter (reference observe/elements.py:21-86)."""

    def process_frame(self, stream, **inputs):
        names, _ = self.get_parameter("inspect", "*")
        target, _ = self.get_parameter("target", "log")
        frame = stream.frames.get(max(stream.frames)) \
            if stream.frames else None
        swag = frame.swag if frame else dict(inputs)
        if names == "*":
            selected = {k: v for k, v in swag.items() if "." not in k}
        else:
            wanted = names if isinstance(names, list) else \
                str(names).split(",")
            selected = {name: swag.get(name) for name in wanted}
        line = f"inspect {self.name}: {selected}"
        if target == "print":
            print(line)
        elif str(target).startswith("file:"):
            with open(str(target)[5:], "a") as fh:
                fh.write(line + "\n")
        else:
            self.logger.info("%s", line)
        return StreamEvent.OKAY, {}


class Metrics(PipelineElement):
    """Tail element reporting per-element wall time in ms (reference
    observe/elements.py:85-126)."""

    def process_frame(self, stream, **inputs):
        frame = stream.frames.get(max(stream.frames)) \
            if stream.frames else None
        if frame is None:
            return StreamEvent.OKAY, {}
        rate, _ = self.get_parameter("metrics_rate", 1)
        count = stream.variables.setdefault(f"{self.name}.count", 0)
        stream.variables[f"{self.name}.count"] = count + 1
        if count % int(rate):
            return StreamEvent.OKAY, {}
        times = {name[:-5]: f"{value * 1000:.2f} ms"
                 for name, value in frame.metrics.items()
                 if name.endswith("_time")}
        self.logger.info("metrics frame %s: %s", frame.frame_id, times)
        return StreamEvent.OKAY, {"metrics": dict(frame.metrics)}
