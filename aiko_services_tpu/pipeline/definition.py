"""Pipeline definitions: typed dataclasses + JSON validation (reference:
src/aiko_services/main/pipeline.py:222-258 dataclasses and the inline Avro
schema at pipeline.py:1693-1822).

The reference validates with Avro; this build uses a hand-rolled validator
with precise error paths (no extra dependency) over the same information:
name, version, runtime, graph (S-expression strings), optional default
parameters, and one entry per element with input/output signatures and a
deploy descriptor (local module / remote service filter).

TPU extension: element definitions may carry a ``placement`` block --
``{"devices": 4, "mesh": {"tp": 4}}`` -- consumed by the tpu substrate to
place the element's compute onto a submesh.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

__all__ = ["PipelineDefinition", "ElementDefinition", "DefinitionError",
           "parse_pipeline_definition", "load_pipeline_definition"]

RUNTIMES = ("python", "jax")


class DefinitionError(ValueError):
    pass


@dataclass
class ElementDefinition:
    name: str
    input: list          # [{"name": ..., "type": ...}]
    output: list
    deploy_local: dict | None = None      # {"module": ..., "class_name": ...}
    deploy_remote: dict | None = None     # ServiceFilter fields
    parameters: dict = field(default_factory=dict)
    placement: dict = field(default_factory=dict)
    # Degraded-mode failover (ISSUE 5): the name of another (locally
    # deployed, off-graph) element definition to run in place of this
    # remote stage while its circuit breaker is open.
    fallback: str | None = None
    # Static-analysis escape hatch (ISSUE 6): ``"lint": ["dead-output"]``
    # suppresses those rules for THIS element in aiko_lint/pre-flight.
    lint_disable: tuple = ()

    @property
    def input_names(self) -> list[str]:
        return [io["name"] for io in self.input]

    @property
    def output_names(self) -> list[str]:
        return [io["name"] for io in self.output]


@dataclass
class PipelineDefinition:
    name: str
    version: int
    runtime: str
    graph: list[str]
    parameters: dict = field(default_factory=dict)
    elements: list[ElementDefinition] = field(default_factory=list)
    # Pipeline-wide lint suppressions (``"lint": [...]`` at top level).
    lint_disable: tuple = ()

    def element(self, name: str) -> ElementDefinition:
        for element in self.elements:
            if element.name == name:
                return element
        raise DefinitionError(
            f"pipeline {self.name!r}: graph node {name!r} has no "
            f"element definition (defined: {self.element_names()})")

    def element_names(self) -> list[str]:
        return [e.name for e in self.elements]


def _require(data: dict, key: str, kind, path: str):
    if key not in data:
        raise DefinitionError(f"{path}: missing required field {key!r}")
    value = data[key]
    if kind is not None and not isinstance(value, kind):
        raise DefinitionError(
            f"{path}.{key}: expected {getattr(kind, '__name__', kind)}, "
            f"got {type(value).__name__}")
    return value


def _parse_io(entries, path: str) -> list:
    if not isinstance(entries, list):
        raise DefinitionError(f"{path}: expected a list")
    result = []
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise DefinitionError(f"{path}[{i}]: expected an object")
        name = _require(entry, "name", str, f"{path}[{i}]")
        io_type = entry.get("type", "any")
        result.append({"name": name, "type": io_type})
    return result


def _parse_lint(value, path: str) -> tuple:
    """``"lint": ["rule-a", ...]`` -- per-definition static-analysis
    suppressions (the JSON twin of ``# aiko-lint: disable=...``).
    Unknown rule ids are rejected: a typo'd suppression that silently
    does nothing is exactly the kind of frame-N surprise lint exists
    to prevent."""
    if value is None:
        return ()
    if not isinstance(value, list) \
            or not all(isinstance(rule, str) for rule in value):
        raise DefinitionError(
            f"{path}.lint: expected a list of rule-id strings")
    from ..analysis.findings import RULES     # dependency-free module

    unknown = sorted(set(value) - set(RULES))
    if unknown:
        raise DefinitionError(
            f"{path}.lint: unknown rule(s) {unknown}; see "
            f"'aiko_lint --rules' for the catalogue")
    return tuple(value)


def _replicas_error(spec) -> str | None:
    """Why a placement ``replicas`` spec is malformed, or None.  Domain:
    a count (int >= 1), ``"auto"`` (the control loop scales 1..pool),
    or ``{"min": lo, "max": hi}`` autoscale bounds with 1 <= lo <= hi."""
    if isinstance(spec, bool):
        return f"replicas must be a count >= 1, 'auto' or " \
               f"{{min, max}}, got {spec!r}"
    if isinstance(spec, int):
        if spec < 1:
            return f"replicas must be >= 1, got {spec}"
        return None
    if isinstance(spec, str):
        if spec.strip().lower() != "auto":
            return f"replicas must be a count >= 1, 'auto' or " \
                   f"{{min, max}}, got {spec!r}"
        return None
    if isinstance(spec, dict):
        if not set(spec) <= {"min", "max"}:
            return f"replicas bounds accept only min/max, " \
                   f"got {sorted(spec)}"
        low, high = spec.get("min", 1), spec.get("max")
        for name, value in (("min", low), ("max", high)):
            if value is not None and (not isinstance(value, int)
                                      or isinstance(value, bool)
                                      or value < 1):
                return f"replicas {name} must be an int >= 1, " \
                       f"got {value!r}"
        if high is not None and low > high:
            return f"replicas min ({low}) must be <= max ({high})"
        return None
    return f"replicas must be a count >= 1, 'auto' or {{min, max}}, " \
           f"got {spec!r}"


def placement_error(block: dict) -> str | None:
    """Why this placement block is malformed, or None.  The ONE
    authority shared by ``Pipeline._build_placement`` (create-time
    raise) and the dataflow analyzer's ``bad-placement`` rule, so the
    two can never drift."""
    if "replicas" in block:
        problem = _replicas_error(block["replicas"])
        if problem:
            return problem
    if "host" in block:
        # Mesh mode (ISSUE 9): pins the stage to one host group of a
        # ``mesh: {hosts: N}`` pipeline; range-checked at carve time
        # (the group count depends on the live mesh).
        host = block["host"]
        if not isinstance(host, int) or isinstance(host, bool) \
                or host < 0:
            return (f"placement host must be a non-negative host "
                    f"index, got {host!r}")
    if "mesh" in block:
        mesh = block["mesh"]
        if not isinstance(mesh, dict) or not mesh or not all(
                isinstance(v, int) and not isinstance(v, bool) and v > 0
                for v in mesh.values()):
            return (f"mesh must map axis names to positive chip "
                    f"counts, got {mesh!r}")
        return None
    if "devices" in block:
        want = block["devices"]
        if isinstance(want, str):
            if want.strip().lower() != "auto":
                return (f"placement devices must be a chip count or "
                        f"'auto', got {want!r}")
        elif not isinstance(want, int) or isinstance(want, bool) \
                or want <= 0:
            return (f"placement devices must be a positive chip "
                    f"count or 'auto', got {want!r}")
        return None
    if "replicas" in block:
        # ``replicas`` without mesh/devices places nothing -- legal at
        # create (the ``replicas-on-unplaced`` lint rule warns), so a
        # definition can declare bounds before committing chips.
        return None
    return f"placement needs 'mesh' or 'devices', got {sorted(block)}"


def parse_pipeline_definition(data: dict | str,
                              source: str = "<definition>") \
        -> PipelineDefinition:
    if isinstance(data, str):
        try:
            data = json.loads(data)
        except json.JSONDecodeError as error:
            raise DefinitionError(f"{source}: invalid JSON: {error}")
    if not isinstance(data, dict):
        raise DefinitionError(f"{source}: definition must be an object")

    name = _require(data, "name", str, source)
    version = data.get("version", 0)
    runtime = data.get("runtime", "jax")
    if runtime not in RUNTIMES:
        raise DefinitionError(
            f"{source}.runtime: {runtime!r} not one of {RUNTIMES}")
    graph = _require(data, "graph", list, source)
    if not graph or not all(isinstance(g, str) for g in graph):
        raise DefinitionError(
            f"{source}.graph: expected non-empty list of S-expression "
            f"strings")
    parameters = data.get("parameters", {})
    if not isinstance(parameters, dict):
        raise DefinitionError(f"{source}.parameters: expected an object")
    lint_disable = _parse_lint(data.get("lint"), source)

    elements_data = _require(data, "elements", list, source)
    elements = []
    seen = set()
    for i, entry in enumerate(elements_data):
        path = f"{source}.elements[{i}]"
        if not isinstance(entry, dict):
            raise DefinitionError(f"{path}: expected an object")
        element_name = _require(entry, "name", str, path)
        if element_name in seen:
            raise DefinitionError(f"{path}: duplicate element "
                                  f"{element_name!r}")
        seen.add(element_name)
        deploy = entry.get("deploy", {})
        deploy_local = deploy.get("local")
        deploy_remote = deploy.get("remote")
        if deploy_local is None and deploy_remote is None:
            raise DefinitionError(
                f"{path}.deploy: needs 'local' (module[, class_name]) or "
                f"'remote' (service filter)")
        if deploy_local is not None:
            _require(deploy_local, "module", str, f"{path}.deploy.local")
        fallback = entry.get("fallback")
        if fallback is not None:
            if not isinstance(fallback, str):
                raise DefinitionError(f"{path}.fallback: expected an "
                                      f"element name string")
            if deploy_remote is None:
                raise DefinitionError(
                    f"{path}.fallback: only remote-deployed elements "
                    f"may declare a fallback")
        elements.append(ElementDefinition(
            name=element_name,
            input=_parse_io(entry.get("input", []), f"{path}.input"),
            output=_parse_io(entry.get("output", []), f"{path}.output"),
            deploy_local=deploy_local,
            deploy_remote=deploy_remote,
            parameters=entry.get("parameters", {}),
            placement=entry.get("placement", {}),
            fallback=fallback,
            lint_disable=_parse_lint(entry.get("lint"), path)))

    names = {element.name for element in elements}
    for element in elements:
        if element.fallback is None:
            continue
        if element.fallback not in names:
            raise DefinitionError(
                f"{source}: element {element.name!r} fallback "
                f"{element.fallback!r} is not a defined element")
        target = next(e for e in elements if e.name == element.fallback)
        if target.deploy_local is None:
            raise DefinitionError(
                f"{source}: fallback {element.fallback!r} must be "
                f"locally deployed (it runs when the remote is down)")

    return PipelineDefinition(name=name, version=version, runtime=runtime,
                              graph=list(graph), parameters=parameters,
                              elements=elements,
                              lint_disable=lint_disable)


def load_pipeline_definition(pathname: str) -> PipelineDefinition:
    with open(pathname) as fh:
        return parse_pipeline_definition(fh.read(), source=pathname)
