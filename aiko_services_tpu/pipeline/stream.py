"""Streams and Frames (reference: src/aiko_services/main/stream.py).

A Stream is a long-lived flow of Frames through a pipeline graph path; a
Frame is one unit of work: its ``swag`` accumulates every element's outputs
as the frame walks the graph (reference stream.py:71-126).  ``swag`` values
are arbitrary Python objects -- in the TPU data plane they are
``jax.Array``s that stay resident in HBM between elements.

Unlike the reference (which shares one mutable swag across threads and has
documented frame-id races, reference pipeline.py:1239-1260), frames here
are owned by exactly one event-loop task at a time: generators hand frames
over by message, never by shared mutation.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any

from .overlap import DEVICE_INFLIGHT_DEFAULT, DeviceWindow

__all__ = ["StreamEvent", "StreamState", "Frame", "Stream",
           "DEFAULT_STREAM_ID", "FIRST_FRAME_ID"]

DEFAULT_STREAM_ID = "0"
FIRST_FRAME_ID = 0


class StreamEvent(enum.Enum):
    """Returned by every element's process_frame (reference
    stream.py:35-52)."""
    OKAY = "okay"              # continue through the graph
    DROP_FRAME = "drop_frame"  # silently stop processing this frame
    ERROR = "error"            # abort frame, stream enters ERROR
    NO_FRAME = "no_frame"      # source has nothing yet (generators only)
    STOP = "stop"              # graceful stream stop after this frame
    LOOP_END = "loop_end"      # Loop element: exit the loop body


class StreamState(enum.Enum):
    START = "start"
    RUN = "run"
    STOP = "stop"
    ERROR = "error"


@dataclass
class Frame:
    frame_id: int
    swag: dict = field(default_factory=dict)
    # LOOP-CONFINED (audited, PR 4): every write happens on the
    # pipeline's event loop -- stage workers and async elements hand
    # timings back through mailbox continuations, never by mutating
    # this dict from their own threads.  The telemetry plane reads it
    # at frame completion on the loop, and responses carry a SNAPSHOT
    # (Pipeline._respond) so queue consumers on other threads never
    # share the live mapping.
    metrics: dict = field(default_factory=dict)
    paused_pe_name: str | None = None    # set while parked at a remote stage
    response_topic: str | None = None    # where process_frame_response goes
    created: float = field(default_factory=time.monotonic)
    # Stage-parallel execution (pipeline/stages.py): the placed stage
    # this frame currently holds an admission credit for, and the
    # StagePlacement generation it was admitted under (a replace() bump
    # between admissions means the frame re-enters on fresh submeshes).
    stage: str | None = None
    stage_generation: int = 0
    # Replicated stages (ISSUE 7): which replica submesh of ``stage``
    # this frame's admission landed on (None for unreplicated stages).
    # The hop transfer, the worker pick and the element's ``self.plan``
    # all key off it; a replica failover replays exactly the frames
    # whose (stage, stage_replica) matches the dead slot.
    stage_replica: int | None = None
    # The stage this frame is QUEUED for (admission denied, waiting for
    # a credit).  Popped waiter tokens are validated against it: a
    # stale token from a destroyed stream must never admit a recreated
    # stream's same-id frame mid-pipeline.
    stage_waiting: str | None = None
    # Undiscovered-remote-stage retries (exponential backoff): how many
    # times this frame has re-posted waiting for discovery.
    remote_retries: int = 0
    # In-order per-stream delivery: ingest-order sequence assigned when
    # stage-parallel execution is active (None -> respond immediately).
    delivery_seq: int | None = None
    # Provenance: bare swag key -> producer element name, for every
    # value an element of THIS frame wrote.  Fused segments consult it
    # before donating a buffer -- ingest/user data is never donatable
    # (the caller may still hold the array, e.g. a device-resident
    # image ring).
    produced: dict = field(default_factory=dict)
    # Distributed frame tracing (observability/): trace_id + root span
    # minted at ingest (or adopted from the forwarding process when the
    # frame arrived over a RemoteStage hop -- trace_remote marks that
    # this process must return its spans in the response).  ``spans``
    # collects completed span dicts; like ``metrics`` it is
    # LOOP-CONFINED: only the pipeline's event loop writes it (stage
    # workers post continuations; hooks fire on the resumed turn).
    trace_id: str | None = None
    trace_parent: str | None = None
    trace_root: str | None = None
    trace_remote: bool = False
    trace_start: float = 0.0
    trace_done: bool = False
    spans: list = field(default_factory=list)
    # Perf stamp set when the frame starts waiting for a placed stage's
    # admission credit; cleared into ``metrics["stage_<s>_wait_ms"]``
    # when the admission lands.
    stage_wait_start: float | None = None
    # Open remote-hop span while parked at a RemoteStage:
    # (node_name, span_id, wall start).
    remote_span: tuple | None = None
    # Failure recovery (ISSUE 5): the frame's absolute deadline
    # (monotonic seconds, None = no deadline), how many times it has
    # been replayed across a device replacement, and the replay epoch
    # -- bumped on every replay so in-flight stage-worker/async
    # completions from the PREVIOUS attempt read as stale when their
    # continuation posts land.
    deadline: float | None = None
    replays: int = 0
    replay_epoch: int = 0
    # Binary data plane (ISSUE 9): the FORWARDING process's tensor-pipe
    # endpoint ("host:port"), carried in the process_frame header so
    # this process can ship the response's tensors back over the pipe
    # instead of base64'ing them onto the control fabric.  None = the
    # origin advertises no pipe; the response rides MQTT whole.
    pipe_reply: str | None = None
    # Elements whose outputs this frame has accepted (map-out ran):
    # the replay frontier.  A replayed frame resumes at the first path
    # node NOT in here -- everything before it is host-visible in the
    # swag and must not re-execute.
    completed: set = field(default_factory=set)
    # Unified QoS admission (ISSUE 12, gateway/qos.py): tenant + class
    # resolved from the stream at ingest, the global ingest sequence
    # (the rank tiebreak that preserves arrival order within a class),
    # when the frame last started WAITING at an admission seam (aging
    # input), whether the near-deadline promotion already fired (it is
    # counted once), and whether the QosScheduler's in-flight
    # accounting is open for this frame (closed exactly once on any
    # completion path).
    tenant: str | None = None
    qos_class: str | None = None
    qos_seq: int = 0
    qos_wait_start: float | None = None
    qos_promoted: bool = False
    qos_open: bool = False


@dataclass
class Stream:
    stream_id: str
    graph_path: str | None = None
    parameters: dict = field(default_factory=dict)
    variables: dict = field(default_factory=dict)
    state: StreamState = StreamState.START
    frames: dict = field(default_factory=dict)      # frame_id -> Frame
    frame_count: int = 0                            # next frame id
    topic_response: str | None = None
    queue_response: Any = None                      # local queue.Queue
    lease: Any = None
    generator_handles: list = field(default_factory=list)
    last_frame_time: float = field(default_factory=time.monotonic)
    # Bounded async-dispatch window: completed frames whose device work
    # may still be computing (jitted elements return un-synced arrays).
    # Paced at ingest so dispatch stays at most ``device_inflight``
    # frames ahead of compute (pipeline/overlap.py).
    device_window: DeviceWindow = field(default_factory=DeviceWindow)
    device_inflight: int = DEVICE_INFLIGHT_DEFAULT
    # Fused device-segment compilation (pipeline/fusion.py): ``fuse``
    # is the resolved ``auto|off`` mode; ``fusion_plans`` memoizes the
    # partition of each execution path (keyed by its node-name tuple)
    # so the fuse decision is made once per stream, not per frame, and
    # ``fusion_segments`` dedupes the segments themselves across plans
    # (the full path and post-async resume suffixes share one compiled
    # segment per member chain).
    fuse: str = "auto"
    fusion_plans: dict = field(default_factory=dict)
    fusion_segments: dict = field(default_factory=dict)
    # In-order per-stream delivery under stage-parallel execution
    # (pipeline/stages.py): frames respond in ingest order even though
    # they complete stage-pipelined.  ``delivery_count`` hands out
    # sequence numbers at ingest; ``delivery_next``/``delivery_pending``
    # form the reorder buffer drained by ``Pipeline._deliver``.
    delivery_count: int = 0
    delivery_next: int = 0
    delivery_pending: dict = field(default_factory=dict)
    # Failure recovery (ISSUE 5), resolved once at stream creation:
    # ``frame_deadline_ms`` (0 = none) stamps every ingested frame's
    # deadline; ``overload_policy``/``overload_limit`` bound the
    # stream's in-flight queue depth for live streams --
    # ``shed_oldest`` cancels the oldest admission-queued frame,
    # ``shed_newest`` refuses the incoming one, ``block`` (default)
    # keeps the pre-existing backpressure-only behavior.
    deadline_ms: float = 0.0
    overload_policy: str = "block"
    overload_limit: int = 0
    # Unified QoS admission (ISSUE 12): the stream's tenant identity
    # and priority class, resolved once at creation (gateway sessions
    # set them via stream parameters; CLI/local streams default to
    # the default tenant's class).  Every frame of a stream inherits
    # them, which is what makes priority reorder across streams but
    # never within one.
    tenant: str = "default"
    qos_class: str = "standard"
    # Durable stream journal (ISSUE 13): whether this stream's
    # recoverable state is journaled (resolved once at creation from
    # the pipeline's ``journal`` parameter; a stream-level
    # ``journal: off`` opts out -- e.g. the gateway's one-shot HTTP
    # streams, which have no session to adopt).
    journal: bool = False

    def next_frame_id(self) -> int:
        frame_id = self.frame_count
        self.frame_count += 1
        return frame_id

    @property
    def in_flight(self) -> int:
        return len(self.frames)
