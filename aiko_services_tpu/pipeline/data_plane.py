"""Binary multi-host data plane for remote-stage frames (ISSUE 9).

The source paper's architecture splits control from data: MQTT carries
discovery, commands and the small per-frame envelope; bulk tensors
must not.  Before this module, every remote-stage hop shipped its
tensors base64'd inside the S-expression ``process_frame`` message --
a ~33% byte tax plus a full host copy per tensor per hop.  Now each
Pipeline binds one :class:`TensorPipeEndpoint` (the length-prefixed
raw-bytes TCP framing from ``transport/tensor_pipe.py``, native or
pure-Python) advertised in its registrar record as a
``tensor_pipe=host:port`` tag, and remote hops ship:

- **pipe**: every array-valued swag entry as raw bytes (dtype-tagged
  integer views for bf16/float8, reusing the codec's tagging), keyed
  by a per-forward ``token``;
- **MQTT**: the control envelope -- frame id, stream id, trace
  context, the token and the key list -- exactly the traffic the
  control fabric is for.

The receiver pairs the two: the envelope *claims* the token's tensors
from the endpoint; tensors still in flight defer the envelope (a
watch fires when they land), and a token whose tensors never arrive
expires -- the same blast radius as a dropped wire frame, recovered by
the sender's deadline/breaker machinery.  Negotiation is automatic:
a peer advertising no pipe rides MQTT (counted, never silent), and a
pipe send failure falls back to MQTT for that frame while the
sender's per-peer :class:`~..faults.CircuitBreaker` paces reconnects
(PR-5 machinery, reused).

Everything here is jax-free; ``device_put`` into the target submesh
happens in the engine (pipeline.py) where the placement lives.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict

import numpy as np

from .codec import tag_view, untag_view
from ..faults import CircuitBreaker
from ..transport.tensor_pipe import (create_pipe_client,
                                     create_pipe_server)
from ..utils import get_logger

__all__ = ["DATA_PLANE_MODES", "PIPE_TAG", "PipeSender",
           "TensorPipeEndpoint", "split_arrays",
           "PIPE_CLAIM_TIMEOUT_MS_DEFAULT",
           "PIPE_TOKEN_CAPACITY_DEFAULT"]

_logger = get_logger("aiko.data_plane")

DATA_PLANE_MODES = ("auto", "tensor_pipe", "mqtt")
#: registrar-record tag key advertising a pipeline's pipe endpoint.
PIPE_TAG = "tensor_pipe"

PIPE_CLAIM_TIMEOUT_MS_DEFAULT = 5000.0
#: tokens whose tensors were claimed stay briefly for duplicate
#: envelopes (MQTT QoS1 redelivery / wire_dup chaos: the duplicate
#: re-claims and re-executes, matching the MQTT path's blast radius),
#: then sweep.
_CLAIMED_TTL_S = 2.0
#: token-store hard cap (``pipe_token_capacity`` parameter): a flood
#: control against pathological senders, NOT the working-set bound --
#: steady-state memory is arrival-rate x TTL, since claimed tokens
#: sweep after _CLAIMED_TTL_S and unclaimed after the claim timeout.
#: Must exceed the realistic in-flight forward count to this endpoint
#: or evicted frames pay the claim timeout (counted, tokens_evicted).
PIPE_TOKEN_CAPACITY_DEFAULT = 128
_PIPE_CONNECT_TIMEOUT_S = 2.0
_PIPE_BREAKER_THRESHOLD = 3
_PIPE_BREAKER_COOLDOWN_S = 1.0


def split_arrays(frame_data: dict) -> dict:
    """The array-valued entries of a host-side frame dict -- exactly
    the values the MQTT codec would base64 (same predicate), i.e. the
    ones that belong on the pipe."""
    return {key: value for key, value in frame_data.items()
            if hasattr(value, "__array__")
            and not isinstance(value, (str, bytes, list, tuple, dict))}


class _Token:
    __slots__ = ("arrays", "arrived", "claimed_at")

    def __init__(self):
        self.arrays: dict = {}
        self.arrived = time.monotonic()
        self.claimed_at: float | None = None


class TensorPipeEndpoint:
    """One pipeline's receive side of the data plane: the pipe server,
    the token store pairing tensors with their MQTT envelopes, and the
    watch/expiry machinery for envelopes that outran their tensors.

    Thread model: a collector thread drains the server queue into the
    token store and fires watch callbacks (which ``post_self`` back
    onto the pipeline's event loop); ``claim``/``watch`` are called
    from the event loop.  All state behind one lock."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 claim_timeout_s: float =
                 PIPE_CLAIM_TIMEOUT_MS_DEFAULT / 1000.0,
                 capacity: int = PIPE_TOKEN_CAPACITY_DEFAULT):
        self.server = create_pipe_server(host, port)
        self.host = host
        self.port = self.server.port
        self.location = f"{host}:{self.port}"
        self.claim_timeout_s = float(claim_timeout_s)
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._tokens: OrderedDict[str, _Token] = OrderedDict()
        # token -> (frozenset(keys), callback, monotonic deadline)
        self._watches: dict[str, tuple] = {}
        self.claims_expired = 0
        self.tokens_evicted = 0
        self._evict_logged = False
        self._closing = threading.Event()
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name="aiko.data_plane.collect")
        self._collector.start()

    # -- receive side ------------------------------------------------------

    def _collect_loop(self):
        while not self._closing.is_set():
            frame = self.server.recv(timeout=0.1)
            fired = []
            now = time.monotonic()
            with self._lock:
                if frame is not None:
                    self._store(frame, fired)
                self._sweep(now, fired)
            for callback in fired:
                try:
                    callback()
                except Exception:
                    _logger.exception("data plane watch callback "
                                      "failed")

    def _store(self, frame, fired: list) -> None:
        name, array = frame
        try:
            meta = json.loads(name)
            token_id = str(meta["t"])
            key = str(meta["k"])
        except (ValueError, KeyError, TypeError):
            _logger.debug("tensor pipe frame with non-data-plane "
                          "name %r ignored", name)
            return
        token = self._tokens.get(token_id)
        if token is None:
            token = self._tokens[token_id] = _Token()
        self._tokens.move_to_end(token_id)
        token.arrays[key] = untag_view(array, meta.get("v"))
        while len(self._tokens) > self._capacity:
            evicted_id, evicted = self._tokens.popitem(last=False)
            if evicted.claimed_at is None:
                # An UNCLAIMED token squeezed out by capacity pressure
                # (>capacity forwards in flight to this endpoint): its
                # envelope will wait out the claim timeout and take the
                # MQTT re-forward -- a latency cliff that must be
                # counted and visible, never silent.
                self.tokens_evicted += 1
                if not self._evict_logged:
                    self._evict_logged = True
                    _logger.warning(
                        "data plane endpoint %s: token store over "
                        "capacity (%d) -- evicting unclaimed token %s; "
                        "its envelope pays the claim timeout + MQTT "
                        "re-forward (see tokens_evicted)",
                        self.location, self._capacity, evicted_id)
        watch = self._watches.get(token_id)
        if watch is not None and watch[0] <= set(token.arrays):
            fired.append(watch[1])
            del self._watches[token_id]

    def _sweep(self, now: float, fired: list) -> None:
        # Expired watches fire their callback anyway: the claimer
        # re-claims, finds the keys still missing, and gives up with a
        # counted log -- the wire-drop blast radius, never a silent
        # hang of the envelope.
        for token_id in [token_id for token_id, (_, _, deadline)
                         in self._watches.items() if now > deadline]:
            self.claims_expired += 1
            fired.append(self._watches.pop(token_id)[1])
        for token_id in [token_id for token_id, token
                         in self._tokens.items()
                         if (token.claimed_at is not None
                             and now - token.claimed_at > _CLAIMED_TTL_S)
                         or now - token.arrived
                         > self.claim_timeout_s + _CLAIMED_TTL_S]:
            del self._tokens[token_id]

    # -- event-loop API ----------------------------------------------------

    def claim(self, token_id: str, keys) -> dict | None:
        """All of ``keys`` present under ``token_id`` -> the arrays
        (the entry stays briefly for duplicate envelopes); else None --
        the caller should ``watch``."""
        with self._lock:
            token = self._tokens.get(str(token_id))
            if token is None or not set(keys) <= set(token.arrays):
                return None
            token.claimed_at = time.monotonic()
            return dict(token.arrays)

    def watch(self, token_id: str, keys, callback) -> None:
        """Fire ``callback`` (from the collector thread; use post_self)
        once every key arrived -- or at the claim timeout, whichever is
        first.  A token already complete fires inline.  A CLOSED
        endpoint fires the timeout path inline too: its collector
        thread is gone, so no deadline would ever be serviced and the
        deferred envelope (plus everything ordered behind it) would
        hang forever instead of taking the counted MQTT re-forward."""
        with self._lock:
            if self._closing.is_set():
                self.claims_expired += 1
                complete = True          # fire below, outside the lock
            else:
                token = self._tokens.get(str(token_id))
                complete = token is not None \
                    and set(keys) <= set(token.arrays)
                if not complete:
                    self._watches[str(token_id)] = (
                        frozenset(str(key) for key in keys), callback,
                        time.monotonic() + self.claim_timeout_s)
        if complete:
            callback()

    @property
    def dropped(self) -> int:
        return self.server.dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._tokens)

    @property
    def stats(self) -> dict:
        with self._lock:
            return {"location": self.location,
                    "tokens": len(self._tokens),
                    "watches": len(self._watches),
                    "claims_expired": self.claims_expired,
                    "tokens_evicted": self.tokens_evicted,
                    "dropped_frames": self.server.dropped}

    def close(self) -> None:
        # _closing is set UNDER the lock so a racing watch() either
        # registers before the drain below (and is fired here) or sees
        # the flag and fires inline -- never a watch stranded on a dead
        # collector.
        with self._lock:
            self._closing.set()
            pending = [watch[1] for watch in self._watches.values()]
            self._watches.clear()
            self.claims_expired += len(pending)
        # join=False: teardown over many pipelines must not pay a
        # thread-join timeout per endpoint; the daemon threads exit on
        # their next poll tick.
        self.server.close(join=False)
        for callback in pending:
            try:
                callback()
            except Exception:
                _logger.exception("data plane watch callback failed "
                                  "during endpoint close")


class PipeSender:
    """One peer endpoint's send side: a lazily-connected pipe client
    behind a :class:`CircuitBreaker` -- the PR-5 reconnect discipline.
    Consecutive send/connect failures open the breaker (frames ride
    MQTT without paying a connect timeout each); the half-open probe is
    simply the next frame's reconnect attempt."""

    def __init__(self, location: str,
                 connect_timeout_s: float = _PIPE_CONNECT_TIMEOUT_S,
                 threshold: int = _PIPE_BREAKER_THRESHOLD,
                 cooldown_s: float = _PIPE_BREAKER_COOLDOWN_S):
        host, _, port = str(location).rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"tensor pipe endpoint {location!r}: "
                             f"expected host:port")
        self.location = str(location)
        self.host, self.port = host, int(port)
        self._connect_timeout_s = float(connect_timeout_s)
        self.breaker = CircuitBreaker(threshold, cooldown_s)
        self._client = None
        self._lock = threading.Lock()
        self.frames_sent = 0
        self.bytes_sent = 0

    def send(self, token_id: str, arrays: dict) -> int | None:
        """Ship ``arrays`` under ``token_id``; returns the wire bytes
        sent, or None on failure / open breaker (the caller falls back
        to the MQTT payload path for this frame -- frames are never
        lost to a data-plane failure)."""
        if not self.breaker.allow():
            return None
        with self._lock:
            try:
                if self._client is None:
                    self._client = create_pipe_client(
                        self.host, self.port,
                        timeout=self._connect_timeout_s)
                total = 0
                for key in sorted(arrays):
                    view, tag = tag_view(np.asarray(arrays[key]))
                    meta = {"t": str(token_id), "k": str(key)}
                    if tag:
                        meta["v"] = tag
                    # send() reports the exact wire bytes (prefix +
                    # header + payload) -- the bench's byte accounting.
                    total += self._client.send(view,
                                               name=json.dumps(meta))
            except (ConnectionError, OSError) as error:
                self._drop_client()
                self.breaker.record_failure()
                _logger.warning("tensor pipe send to %s failed (%s); "
                                "frame falls back to MQTT",
                                self.location, error)
                return None
            self.breaker.record_success()
            self.frames_sent += 1
            self.bytes_sent += total
            return total

    def _drop_client(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    @property
    def stats(self) -> dict:
        return {"location": self.location,
                "frames_sent": self.frames_sent,
                "bytes_sent": self.bytes_sent,
                "breaker": self.breaker.state}

    def close(self) -> None:
        with self._lock:
            self._drop_client()
