"""Frame wire codec: swag values <-> S-expression-safe strings.

Local (in-process) frames never touch this -- swag values including
``jax.Array``s pass by reference.  Only frames crossing a process boundary
on the *control* fabric are encoded: scalars/lists/dicts as S-expression
terms, numpy/jax arrays as base64 .npy blobs (the equivalent of the
reference's PE_DataEncode/Decode elements, reference
examples/pipeline/elements.py:214-246).  Bulk tensor traffic should use
the tensor transport (tpu/transfer) instead; this codec is the correctness
fallback, not the fast path.
"""

from __future__ import annotations

import base64
import io

import numpy as np

__all__ = ["encode_value", "decode_value", "encode_frame_data",
           "decode_frame_data"]

_NPY_PREFIX = "npy64:"


def encode_value(value):
    if hasattr(value, "__array__") and not isinstance(
            value, (str, bytes, list, tuple, dict)):
        array = np.asarray(value)
        buffer = io.BytesIO()
        np.save(buffer, array, allow_pickle=False)
        return _NPY_PREFIX + base64.b64encode(buffer.getvalue()).decode()
    if isinstance(value, (list, tuple)):
        return [encode_value(v) for v in value]
    if isinstance(value, dict):
        return {k: encode_value(v) for k, v in value.items()}
    return value


def decode_value(value):
    if isinstance(value, str) and value.startswith(_NPY_PREFIX):
        raw = base64.b64decode(value[len(_NPY_PREFIX):])
        return np.load(io.BytesIO(raw), allow_pickle=False)
    if isinstance(value, list):
        return [decode_value(v) for v in value]
    if isinstance(value, dict):
        return {k: decode_value(v) for k, v in value.items()}
    return value


def encode_frame_data(frame_data: dict) -> dict:
    return {name: encode_value(value) for name, value in frame_data.items()}


def decode_frame_data(frame_data: dict) -> dict:
    return {name: decode_value(value) for name, value in frame_data.items()}
