"""Frame wire codec: swag values <-> S-expression-safe strings.

Local (in-process) frames never touch this -- swag values including
``jax.Array``s pass by reference, and with the device-resident swag
contract (pipeline/overlap.py) they stay in HBM between elements.  Only
frames crossing a process boundary on the *control* fabric are encoded,
and the boundary is EXPLICIT: the engine fetches every device leaf with
one counted ``TransferLedger.fetch`` (a single ``jax.device_get``)
before calling :func:`encode_frame_data`, so this codec only ever sees
host values -- an encode is never the hidden device sync it was when
``np.asarray`` here was the fetch.  Scalars/lists/dicts encode as
S-expression terms, host arrays as base64 .npy blobs (the equivalent of
the reference's PE_DataEncode/Decode elements, reference
examples/pipeline/elements.py:214-246).  Extension dtypes (bfloat16 and
friends -- ml_dtypes, which .npy cannot represent: they round-trip as
raw ``V2`` bytes and lose the dtype) ride a tagged integer view
instead.  Bulk tensor traffic should use the tensor transport
(tpu/transfer); this codec is the correctness fallback, not the fast
path.
"""

from __future__ import annotations

import base64
import io

import numpy as np

__all__ = ["encode_value", "decode_value", "encode_frame_data",
           "decode_frame_data", "tag_view", "untag_view"]

_NPY_PREFIX = "npy64:"
# Extension-dtype arrays (ml_dtypes: bfloat16, float8_*...):
# ``npyt:<dtype_name>:<base64 npy of the same-itemsize integer view>``.
# The integer view preserves shape (0-d included) and byte layout; the
# tag restores the dtype on decode.
_NPYT_PREFIX = "npyt:"
_VIEW_BY_ITEMSIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32}


def _extension_dtype(dtype: np.dtype) -> bool:
    """True only for ml_dtypes extension dtypes the tagged view can
    restore; plain/structured void dtypes fall back to the npy path."""
    if dtype.kind != "V" or dtype.names is not None \
            or dtype.itemsize not in _VIEW_BY_ITEMSIZE:
        return False
    import ml_dtypes
    return hasattr(ml_dtypes, dtype.name)


def _save_npy(array: np.ndarray) -> str:
    buffer = io.BytesIO()
    np.save(buffer, array, allow_pickle=False)
    return base64.b64encode(buffer.getvalue()).decode()


def encode_value(value):
    if hasattr(value, "__array__") and not isinstance(
            value, (str, bytes, list, tuple, dict)):
        array = np.asarray(value)
        if _extension_dtype(array.dtype):
            # ml_dtypes extension dtype: npy would strip it to raw
            # bytes.  Encode the integer view + a dtype tag.
            view = _VIEW_BY_ITEMSIZE[array.dtype.itemsize]
            return (f"{_NPYT_PREFIX}{array.dtype.name}:"
                    f"{_save_npy(array.view(view))}")
        return _NPY_PREFIX + _save_npy(array)
    if isinstance(value, (list, tuple)):
        return [encode_value(v) for v in value]
    if isinstance(value, dict):
        return {k: encode_value(v) for k, v in value.items()}
    return value


def _load_npy(data: str) -> np.ndarray:
    raw = base64.b64decode(data)
    return np.load(io.BytesIO(raw), allow_pickle=False)


def decode_value(value):
    if isinstance(value, str) and value.startswith(_NPY_PREFIX):
        return _load_npy(value[len(_NPY_PREFIX):])
    if isinstance(value, str) and value.startswith(_NPYT_PREFIX):
        dtype_name, _, payload = value[len(_NPYT_PREFIX):].partition(":")
        import ml_dtypes
        if not hasattr(ml_dtypes, dtype_name):
            raise ValueError(
                f"codec: unknown extension dtype {dtype_name!r}")
        dtype = np.dtype(getattr(ml_dtypes, dtype_name))
        return _load_npy(payload).view(dtype)
    if isinstance(value, list):
        return [decode_value(v) for v in value]
    if isinstance(value, dict):
        return {k: decode_value(v) for k, v in value.items()}
    return value


def tag_view(array: np.ndarray) -> tuple[np.ndarray, str | None]:
    """(wire array, dtype tag): extension dtypes (bfloat16, float8_*)
    cross binary transports as same-itemsize integer VIEWS plus a name
    tag -- the exact tagging the ``npyt:`` string path above uses, so
    the tensor-pipe data plane and the MQTT codec can never disagree on
    how bf16 round-trips.  Plain dtypes pass through untagged."""
    array = np.asarray(array)
    if _extension_dtype(array.dtype):
        return array.view(_VIEW_BY_ITEMSIZE[array.dtype.itemsize]), \
            array.dtype.name
    return array, None


def untag_view(array: np.ndarray, tag: str | None) -> np.ndarray:
    """Restore a :func:`tag_view` integer view to its tagged dtype."""
    if not tag:
        return array
    import ml_dtypes
    if not hasattr(ml_dtypes, tag):
        raise ValueError(f"codec: unknown extension dtype {tag!r}")
    return array.view(np.dtype(getattr(ml_dtypes, tag)))


def encode_frame_data(frame_data: dict) -> dict:
    return {name: encode_value(value) for name, value in frame_data.items()}


def decode_frame_data(frame_data: dict) -> dict:
    return {name: decode_value(value) for name, value in frame_data.items()}
