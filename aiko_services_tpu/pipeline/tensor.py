"""TPU data-plane substrate: tensor frames, shape bucketing, jit caches,
stage placement on device submeshes (SURVEY.md section 7 step 5).

In the reference, frames crossing stages are S-expressions over MQTT and
bulk data rides ZMQ (reference main/pipeline.py:1328-1347,
elements/media/scheme_zmq.py:40-150).  Here the data plane is TPU-native:

- swag values are ``jax.Array``s resident in HBM between elements;
- a stage is *placed* on a submesh of the local chips
  (``StagePlacement``), and frames hop stages by ``jax.device_put`` --
  resharding over ICI, never through the host;
- XLA recompilation is controlled by bucketing dynamic shapes
  (``ShapeBucketer``) and by per-element compiled-function caches keyed
  on abstract shapes (``JitCache``);
- only when a frame must leave the process (remote stage over the
  control plane, ZMQ scheme) is it encoded host-side
  (``encode_array``/``decode_array``).
"""

from __future__ import annotations

import io
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import MeshPlan, P, make_mesh
from .element import PipelineElement
from .stream import Stream, StreamEvent

__all__ = ["ShapeBucketer", "JitCache", "StagePlacement", "TPUElement",
           "encode_array", "decode_array", "tree_device_put"]


# ---------------------------------------------------------------------------
# Shape bucketing: dynamic sizes -> small set of compiled shapes.

class ShapeBucketer:
    """Round ragged dimensions up to a bucket so XLA compiles once per
    bucket instead of once per length (SURVEY.md section 7 "shape
    polymorphism" hard part).

    Default buckets are powers of two from ``minimum``; an explicit
    bucket list wins.  ``pad(array, axis)`` returns (padded, true_size).
    """

    def __init__(self, buckets: Sequence[int] | None = None,
                 minimum: int = 16, maximum: int = 1 << 20):
        self._buckets = sorted(buckets) if buckets else None
        self._minimum = minimum
        self._maximum = maximum

    def bucket(self, size: int) -> int:
        if self._buckets:
            for b in self._buckets:
                if size <= b:
                    return b
            raise ValueError(f"size {size} exceeds largest bucket "
                             f"{self._buckets[-1]}")
        b = self._minimum
        while b < size:
            b <<= 1
            if b > self._maximum:
                raise ValueError(f"size {size} exceeds maximum bucket")
        return b

    def pad(self, array, axis: int = 0, fill=0):
        size = array.shape[axis]
        target = self.bucket(size)
        if target == size:
            return array, size
        widths = [(0, 0)] * array.ndim
        widths[axis] = (0, target - size)
        return jnp.pad(array, widths, constant_values=fill), size


# ---------------------------------------------------------------------------
# Per-element compiled-function cache.

class JitCache:
    """Cache ``jax.jit`` computations keyed on input avals.

    ``cache(fn)(*args)`` compiles once per distinct (shape, dtype)
    signature and replays thereafter; ``stats`` exposes hit/miss/entry
    counters for the Metrics element, the dashboard share dict
    (``Pipeline.jit_stats``) and the bench's ``jit_cache_*`` keys.
    Donation and shardings pass through to ``jax.jit``.
    """

    def __init__(self, **jit_kwargs):
        self._jit_kwargs = jit_kwargs
        self._compiled: dict = {}
        self.hits = 0
        self.misses = 0

    def _key(self, fn, args, kwargs):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = tuple(
            (leaf.shape, str(leaf.dtype)) if hasattr(leaf, "shape")
            else repr(leaf) for leaf in leaves)
        return (id(fn), treedef, sig)

    def probe(self, fn, args: tuple, kwargs: dict | None = None) -> bool:
        """True when a call with these arguments would MISS (trace +
        compile) -- lets callers time/annotate first-use compiles
        without racing the counters."""
        return self._key(fn, args, kwargs or {}) not in self._compiled

    def __call__(self, fn: Callable) -> Callable:
        jitted = jax.jit(fn, **self._jit_kwargs)

        def wrapper(*args, **kwargs):
            key = self._key(fn, args, kwargs)
            if key in self._compiled:
                self.hits += 1
            else:
                self.misses += 1
                self._compiled[key] = True
            return jitted(*args, **kwargs)

        wrapper.jitted = jitted
        return wrapper

    @property
    def entries(self) -> int:
        return len(self._compiled)

    @property
    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._compiled),
                "signatures": len(self._compiled)}


# ---------------------------------------------------------------------------
# Stage placement: pipeline stages onto disjoint chip submeshes.

class StagePlacement:
    """Carve the local device set into per-stage submeshes.

    The reference deploys stages into other OS processes found by
    ServiceFilter (reference pipeline.py:246-258); on TPU a stage lands
    on a group of local chips instead.  ``assign`` partitions devices
    contiguously (contiguity = ICI neighbours on a pod) and returns a
    ``MeshPlan`` per stage; ``transfer`` reshards a frame's tensors onto
    the next stage's mesh -- on TPU this is a pure ICI copy.
    """

    def __init__(self, devices: Sequence | None = None):
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.plans: dict[str, MeshPlan] = {}
        self._requests: dict[str, dict[str, int]] = {}
        self.generation = 0             # bumped by every replace()

    def assign(self, stages: dict[str, dict[str, int] | int]) \
            -> dict[str, MeshPlan]:
        """stages: name -> chip count or {axis: size} mesh request."""
        requests = {}
        for name, want in stages.items():
            axes = {"dp": want} if isinstance(want, int) else dict(want)
            requests[name] = axes
        total = sum(int(np.prod(list(axes.values())))
                    for axes in requests.values())
        if total > len(self.devices):
            raise ValueError(
                f"stages want {total} devices, have {len(self.devices)}")
        self._requests = requests
        cursor = 0
        for name, axes in requests.items():
            count = int(np.prod(list(axes.values())))
            chunk = self.devices[cursor:cursor + count]
            cursor += count
            self.plans[name] = MeshPlan(make_mesh(axes, chunk))
        return self.plans

    def replace(self, failed_devices: Sequence) -> dict[str, MeshPlan]:
        """Re-place every stage onto the surviving devices (SURVEY.md
        §5.3 TPU-equiv: re-shard onto surviving chips).

        Failed devices leave the pool permanently; stage mesh requests
        shrink by halving their largest axis (power-of-two steps keep
        dp/tp/fsdp shardings valid) until the total fits the survivors.
        Plans are rebuilt in place -- elements must drop cached plans
        and re-put weights (``TPUElement.on_replacement``)."""
        failed = set(failed_devices)
        survivors = [d for d in self.devices if d not in failed]
        if len(survivors) == len(self.devices):
            return self.plans
        if not survivors:
            raise RuntimeError("no surviving devices to re-place onto")
        requests = {name: dict(axes)
                    for name, axes in self._requests.items()}

        def total(reqs):
            return sum(int(np.prod(list(axes.values())))
                       for axes in reqs.values())

        while total(requests) > len(survivors):
            # Shrink the stage holding the most chips, on its largest
            # axis; every request bottoms out at one chip.
            name = max(requests,
                       key=lambda n: int(np.prod(
                           list(requests[n].values()))))
            axes = requests[name]
            axis = max(axes, key=axes.get)
            if axes[axis] <= 1:
                raise RuntimeError(
                    f"cannot shrink stage {name!r} below one device "
                    f"({len(survivors)} survivors for "
                    f"{len(requests)} stages)")
            axes[axis] = max(1, axes[axis] // 2)
        self.devices = survivors
        self.plans = {}
        self.assign(requests)
        self.generation += 1
        return self.plans

    def plan(self, stage: str) -> MeshPlan:
        return self.plans[stage]

    def transfer(self, value, to_stage: str, *spec):
        """Reshard ``value`` (array or pytree) onto a stage's mesh."""
        plan = self.plans[to_stage]
        sharding = plan.shard(*spec) if spec else plan.replicated()
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding)
            if hasattr(leaf, "shape") else leaf, value)


def tree_device_put(tree, plan: MeshPlan, spec: P | None = None):
    """device_put every array leaf of a swag/pytree onto ``plan``."""
    sharding = plan.shard(spec) if spec is not None else plan.replicated()
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, sharding)
        if hasattr(leaf, "shape") else leaf, tree)


# ---------------------------------------------------------------------------
# Host-side array codec (only for frames leaving the process).

def encode_array(array) -> bytes:
    """jax/numpy array -> self-describing bytes (npy format)."""
    buffer = io.BytesIO()
    np.save(buffer, np.asarray(array), allow_pickle=False)
    return buffer.getvalue()


def decode_array(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


# ---------------------------------------------------------------------------
# TPU element base class.

class TPUElement(PipelineElement):
    """PipelineElement hosting jitted computation on a device mesh.

    Placement resolves from the ``placement`` parameter: ``"local"``
    (all local devices, default), a mesh request like
    ``{"dp": 2, "tp": 4}``, or a stage name previously assigned on the
    pipeline's StagePlacement.  Subclasses use ``self.jit`` for
    shape-keyed compiled caches and ``self.plan`` for shardings.

    TPU elements are ``device_resident``: outputs may stay un-synced
    ``jax.Array`` (the engine only syncs at sinks / the bounded dispatch
    window), and event-loop execution runs under the pipeline's
    transfer guard (pipeline/overlap.py).
    """

    device_resident = True

    def __init__(self, context):
        super().__init__(context)
        self._plan: MeshPlan | None = None
        self.jit_cache = JitCache()
        self.bucketer = ShapeBucketer()

    @property
    def plan(self) -> MeshPlan:
        if self._plan is None:
            self._plan = self._resolve_placement()
        return self._plan

    def _resolve_placement(self) -> MeshPlan:
        placement, _ = self.get_parameter("placement", "local")
        placements = getattr(self.pipeline, "stage_placement", None)
        if placements is not None:
            # A definition ``placement`` block registers the stage under
            # the element's own node name; the ``placement`` parameter
            # may also name another stage explicitly (shared submesh).
            for key in (placement, self.name):
                if isinstance(key, str) and key in placements.plans:
                    return placements.plan(key)
        # Device pool: the StagePlacement's (which excludes chips removed
        # by replace()) when one exists, else all local devices -- a
        # default-placed element must never re-resolve onto a dead chip.
        pool = list(placements.devices) if placements is not None \
            else list(jax.devices())
        if isinstance(placement, dict):
            axes = dict(placement)
            sizes = list(axes.values())
            if -1 not in sizes and int(np.prod(sizes)) <= len(pool):
                return MeshPlan(make_mesh(axes,
                                          pool[:int(np.prod(sizes))]))
            return MeshPlan(make_mesh(axes, pool))
        return MeshPlan(make_mesh({"dp": len(pool)}, pool))

    def jit(self, fn: Callable) -> Callable:
        """Shape-keyed compiled cache for this element."""
        return self.jit_cache(fn)

    def on_replacement(self):
        """Devices were re-placed under this element (chip failure ->
        ``StagePlacement.replace``): drop the cached plan and compiled
        functions so the next frame resolves the new submesh and
        recompiles there.  Model-hosting subclasses also drop their
        resident weights, which rebuild lazily -- from the
        ``checkpoint`` parameter when set, so recovery restores real
        weights, not random init."""
        self._plan = None
        self.jit_cache = JitCache()

    def put(self, value, *spec):
        """Place an array (or pytree) on this element's mesh."""
        sharding = (self.plan.shard(*spec) if spec
                    else self.plan.replicated())
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding)
            if hasattr(leaf, "shape") else leaf, value)

    def metrics(self) -> dict:
        return {"jit": self.jit_cache.stats,
                "mesh": dict(self.plan.mesh.shape)}
