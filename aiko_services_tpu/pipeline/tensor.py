"""TPU data-plane substrate: tensor frames, shape bucketing, jit caches,
stage placement on device submeshes (SURVEY.md section 7 step 5).

In the reference, frames crossing stages are S-expressions over MQTT and
bulk data rides ZMQ (reference main/pipeline.py:1328-1347,
elements/media/scheme_zmq.py:40-150).  Here the data plane is TPU-native:

- swag values are ``jax.Array``s resident in HBM between elements;
- a stage is *placed* on a submesh of the local chips
  (``StagePlacement``), and frames hop stages by ``jax.device_put`` --
  resharding over ICI, never through the host;
- XLA recompilation is controlled by bucketing dynamic shapes
  (``ShapeBucketer``) and by per-element compiled-function caches keyed
  on abstract shapes (``JitCache``);
- only when a frame must leave the process (remote stage over the
  control plane, ZMQ scheme) is it encoded host-side
  (``encode_array``/``decode_array``).
"""

from __future__ import annotations

import io
import json
import os
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import MeshPlan, NamedSharding, P, make_mesh
from .element import PipelineElement
from .stream import Stream, StreamEvent

__all__ = ["ShapeBucketer", "JitCache", "StagePlacement", "TPUElement",
           "encode_array", "decode_array", "tree_device_put",
           "device_sort_key", "distributed_mesh_spec",
           "ensure_distributed"]


# ---------------------------------------------------------------------------
# Multi-host mesh mode (ISSUE 9): one logical pipeline spanning
# processes/hosts via jax.distributed, so placed-stage hops ride
# ICI/DCN through the shared global mesh instead of the broker.

MESH_ENV_HOSTS = "AIKO_MESH_HOSTS"
MESH_ENV_COORDINATOR = "AIKO_MESH_COORDINATOR"
MESH_ENV_PROCESS_ID = "AIKO_MESH_PROCESS_ID"

_DISTRIBUTED_STATE = {"initialized": False}


def distributed_mesh_spec(parameters) -> dict | None:
    """The pipeline's multi-host mesh request, or None.

    Sources, in precedence order: the ``mesh`` pipeline parameter
    (``{"hosts": N, "coordinator": "host:port", "process_id": k}`` --
    a dict or its JSON string), then the ``AIKO_MESH_*`` environment
    (hosts / coordinator / process id), so a launcher can mesh-enable
    an unmodified definition per process.  Raises ValueError on a
    malformed spec -- the same validation the ``bad-parameter`` lint
    rule applies at create time."""
    spec = (parameters or {}).get("mesh")
    if isinstance(spec, str):
        try:
            spec = json.loads(spec)
        except json.JSONDecodeError as error:
            raise ValueError(f"mesh: unparseable JSON ({error})")
    if spec is None:
        hosts_env = os.environ.get(MESH_ENV_HOSTS)
        if not hosts_env:
            return None
        spec = {"hosts": hosts_env,
                "coordinator": os.environ.get(MESH_ENV_COORDINATOR),
                "process_id": os.environ.get(MESH_ENV_PROCESS_ID, 0)}
    if not isinstance(spec, dict) or "hosts" not in spec:
        raise ValueError(
            f"mesh: expected {{'hosts': N, ...}}, got {spec!r}")
    try:
        hosts = int(spec["hosts"])
    except (TypeError, ValueError):
        raise ValueError(f"mesh: hosts={spec['hosts']!r} is not an "
                         f"integer")
    if hosts < 1:
        raise ValueError(f"mesh: hosts must be >= 1, got {hosts}")
    try:
        process_id = int(spec.get("process_id") or 0)
    except (TypeError, ValueError):
        raise ValueError(f"mesh: process_id="
                         f"{spec.get('process_id')!r} is not an "
                         f"integer")
    return {"hosts": hosts,
            "coordinator": spec.get("coordinator") or None,
            "process_id": process_id}


def ensure_distributed(spec: dict | None) -> tuple[int, int]:
    """Bring up ``jax.distributed`` for a REAL multi-host mesh (a
    coordinator is configured and more than one host declared), once
    per process; afterwards ``jax.devices()`` is the GLOBAL pool and
    :class:`StagePlacement` groups it by ``device.process_index``.
    Single-process/virtual meshes (no coordinator -- the CI shape)
    skip the bring-up and carve virtual host groups instead.  Returns
    (process_index, process_count)."""
    if spec and spec.get("coordinator") and spec["hosts"] > 1 \
            and not _DISTRIBUTED_STATE["initialized"] \
            and jax.process_count() == 1:
        jax.distributed.initialize(
            coordinator_address=spec["coordinator"],
            num_processes=spec["hosts"],
            process_id=spec["process_id"])
        _DISTRIBUTED_STATE["initialized"] = True
    return jax.process_index(), jax.process_count()


# ---------------------------------------------------------------------------
# Shape bucketing: dynamic sizes -> small set of compiled shapes.

class ShapeBucketer:
    """Round ragged dimensions up to a bucket so XLA compiles once per
    bucket instead of once per length (SURVEY.md section 7 "shape
    polymorphism" hard part).

    Default buckets are powers of two from ``minimum``; an explicit
    bucket list wins.  ``pad(array, axis)`` returns (padded, true_size).
    """

    def __init__(self, buckets: Sequence[int] | None = None,
                 minimum: int = 16, maximum: int = 1 << 20):
        self._buckets = sorted(buckets) if buckets else None
        self._minimum = minimum
        self._maximum = maximum

    def bucket(self, size: int) -> int:
        if self._buckets:
            for b in self._buckets:
                if size <= b:
                    return b
            raise ValueError(f"size {size} exceeds largest bucket "
                             f"{self._buckets[-1]}")
        b = self._minimum
        while b < size:
            b <<= 1
            if b > self._maximum:
                raise ValueError(f"size {size} exceeds maximum bucket")
        return b

    def pad(self, array, axis: int = 0, fill=0):
        size = array.shape[axis]
        target = self.bucket(size)
        if target == size:
            return array, size
        widths = [(0, 0)] * array.ndim
        widths[axis] = (0, target - size)
        return jnp.pad(array, widths, constant_values=fill), size


# ---------------------------------------------------------------------------
# Per-element compiled-function cache.

class JitCache:
    """Cache ``jax.jit`` computations keyed on input avals.

    ``cache(fn)(*args)`` compiles once per distinct (shape, dtype)
    signature and replays thereafter; ``stats`` exposes hit/miss/entry
    counters for the Metrics element, the dashboard share dict
    (``Pipeline.jit_stats``) and the bench's ``jit_cache_*`` keys.
    Donation and shardings pass through to ``jax.jit``.
    """

    def __init__(self, **jit_kwargs):
        self._jit_kwargs = jit_kwargs
        self._compiled: dict = {}
        self.hits = 0
        self.misses = 0

    def _key(self, fn, args, kwargs, context=None):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = tuple(
            (leaf.shape, str(leaf.dtype)) if hasattr(leaf, "shape")
            else repr(leaf) for leaf in leaves)
        return (id(fn), treedef, sig, context)

    def probe(self, fn, args: tuple, kwargs: dict | None = None,
              context=None) -> bool:
        """True when a call with these arguments would MISS (trace +
        compile) -- lets callers time/annotate first-use compiles
        without racing the counters.  ``context`` partitions the key
        space: a replicated stage's submeshes share avals but not
        executables (jax re-specializes per sharding), so dispatchers
        pass the replica index to keep hit/miss/probe accounting
        honest per replica."""
        return self._key(fn, args, kwargs or {}, context) \
            not in self._compiled

    def __call__(self, fn: Callable) -> Callable:
        jitted = jax.jit(fn, **self._jit_kwargs)

        def wrapper(*args, _cache_context=None, **kwargs):
            key = self._key(fn, args, kwargs, _cache_context)
            if key in self._compiled:
                self.hits += 1
            else:
                self.misses += 1
                self._compiled[key] = True
            return jitted(*args, **kwargs)

        wrapper.jitted = jitted
        return wrapper

    @property
    def entries(self) -> int:
        return len(self._compiled)

    @property
    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._compiled),
                "signatures": len(self._compiled)}


# ---------------------------------------------------------------------------
# Stage placement: pipeline stages onto disjoint chip submeshes.

def device_sort_key(device):
    """ICI-topology order for carving contiguous stage chunks: TPU chip
    ``coords`` (x, y, z) then core, so consecutive devices in the sorted
    pool are ICI neighbours and adjacent stages' chunks touch.  Devices
    without coords (CPU/GPU virtual devices) fall back to id order,
    which is the enumeration order of the virtual mesh."""
    coords = getattr(device, "coords", None)
    if coords is not None:
        try:
            return (0, tuple(int(c) for c in coords),
                    int(getattr(device, "core_on_chip", 0) or 0))
        except (TypeError, ValueError):
            pass
    return (1, (), int(getattr(device, "id", 0)))


class StagePlacement:
    """Carve the local device set into per-stage submeshes.

    The reference deploys stages into other OS processes found by
    ServiceFilter (reference pipeline.py:246-258); on TPU a stage lands
    on a group of local chips instead.  ``assign`` partitions the
    topology-sorted device pool contiguously (``device_sort_key``: chip
    coords, so "contiguous" means ICI neighbours and adjacent stages are
    ICI-adjacent) and returns a ``MeshPlan`` per stage; ``transfer``
    reshards a frame's tensors onto the next stage's mesh -- on TPU a
    pure ICI copy, dispatched asynchronously (``jax.device_put`` does
    not block) with the ``NamedSharding`` memoized per
    (stage, generation, spec) and already-resident leaves passed through
    untouched.

    A stage may request ``"auto"`` devices: after fixed requests are
    carved, the remaining pool splits across the auto stages
    proportionally to their measured per-element cost
    (``record_cost``, fed from profiled element spans; equal split
    until profiles exist).  ``replace()`` re-resolves auto splits
    against the survivors, so the balance tracks both the profile and
    the shrinking pool.

    Replicated stages (ISSUE 7): ``assign(..., replicas={stage: N})``
    splits a stage's allocation into N data-parallel **replica
    submeshes** -- contiguous slices of the topology-sorted chunk, each
    its own MeshPlan, so ICI locality holds within a replica.  Fixed
    requests describe ONE replica (total = prod(axes) * N, so
    power-of-two per-replica shapes stay power-of-two); ``auto``
    requests split the stage's cost-proportional share near-equally
    across the replicas.  ``drop_replica`` retires ONE replica's chips
    without touching any peer's submesh (the peer-shedding failover
    path: generation does NOT bump); ``reassign()`` re-fits the
    original requests to the surviving pool (shedding replicas down to
    ``replica_min`` before halving fixed axes) -- the background
    rebuild after a failover, and the autoscaler's re-split.
    """

    def __init__(self, devices: Sequence | None = None):
        self.devices = sorted(devices if devices is not None
                              else jax.devices(), key=device_sort_key)
        self.plans: dict[str, MeshPlan] = {}
        self._requests: dict = {}
        # Multi-host mesh mode (ISSUE 9): the pool partitions into
        # per-host device groups -- by ``device.process_index`` under a
        # real jax.distributed mesh, or N contiguous virtual groups of
        # the topology-sorted pool in a single process (the CI shape,
        # same carving code).  Stages land wholly inside ONE host's
        # group (``stage_hosts``), so a stage hop between same-host
        # stages is ICI and a cross-host hop is DCN through the shared
        # global mesh -- never the broker.
        self.hosts: int | None = None
        self.host_groups: list[list] = []
        self.stage_hosts: dict[str, int] = {}
        self._stage_host_pins: dict[str, int] = {}
        self.generation = 0             # bumped by every replace()
        self.costs: dict[str, float] = {}    # stage -> EMA seconds/frame
        self._shardings: dict = {}      # (stage, replica, gen, spec) memo
        self.transfer_puts = 0          # leaves actually moved
        self.transfer_skipped = 0       # leaves already resident
        # Replicated stages: stage -> [MeshPlan | None per slot] (None =
        # dead, retired by drop_replica), the DESIRED counts (what
        # reassign restores toward), and the floor replica counts the
        # fit loop respects when shedding.  ``replica_epoch`` bumps on
        # every drop/reassign so per-replica plan caches (TPUElement)
        # invalidate without a full-generation bump.
        self.replica_plans: dict[str, list] = {}
        self._replica_desired: dict[str, int] = {}
        self._replica_min: dict[str, int] = {}
        self.replica_epoch = 0

    # -- carving -----------------------------------------------------------

    @staticmethod
    def _normalize(stages: dict) -> dict:
        requests = {}
        for name, want in stages.items():
            if isinstance(want, str):
                if want.strip().lower() != "auto":
                    raise ValueError(
                        f"stage {name!r}: device request must be a chip "
                        f"count, a mesh dict, or 'auto', got {want!r}")
                requests[name] = "auto"
            else:
                requests[name] = {"dp": want} if isinstance(want, int) \
                    else dict(want)
        return requests

    def _resolve(self, requests: dict, pool: int,
                 replicas: dict | None = None) -> dict[str, int]:
        """Resolve every stage to a TOTAL device count against a pool of
        ``pool`` devices.  Fixed requests describe one replica, so a
        replicated fixed stage takes prod(axes) * N; ``auto`` stages
        split the free chips proportionally to recorded per-stage cost,
        floored at one chip per replica."""
        replicas = replicas or {}

        def floor_of(name):
            return max(1, replicas.get(name, 1))

        fixed = {name: int(np.prod(list(axes.values())))
                 * replicas.get(name, 1)
                 for name, axes in requests.items() if axes != "auto"}
        auto = [name for name, axes in requests.items() if axes == "auto"]
        fixed_total = sum(fixed.values())
        auto_floor = sum(floor_of(name) for name in auto)
        if fixed_total + auto_floor > pool:
            raise ValueError(
                f"stages want {fixed_total + auto_floor} devices, "
                f"have {pool}")
        shares: dict[str, int] = {}
        if auto:
            free = pool - fixed_total
            weights = {name: max(float(self.costs.get(name, 0.0)), 0.0)
                       for name in auto}
            if not any(weights.values()):
                weights = {name: 1.0 for name in auto}   # unprofiled
            else:
                # A stage with no profile yet gets the smallest known
                # weight rather than zero chips.
                floor = min(w for w in weights.values() if w > 0)
                weights = {name: (w if w > 0 else floor)
                           for name, w in weights.items()}
            total_w = sum(weights.values())
            shares = {name: max(floor_of(name),
                                int(free * weights[name] / total_w))
                      for name in auto}
            # Largest-remainder fit to exactly ``free`` chips.
            while sum(shares.values()) > free:
                name = max((n for n in auto
                            if shares[n] > floor_of(n)),
                           key=lambda n: shares[n])
                shares[name] -= 1
            while sum(shares.values()) < free:
                name = max(auto, key=lambda n: (
                    free * weights[n] / total_w - shares[n]))
                shares[name] += 1
        return {name: (shares[name] if axes == "auto" else fixed[name])
                for name, axes in requests.items()}

    def assign(self, stages: dict, costs: dict | None = None,
               replicas: dict | None = None,
               replica_min: dict | None = None,
               hosts: int | None = None,
               stage_hosts: dict | None = None) -> dict[str, MeshPlan]:
        """stages: name -> chip count, {axis: size} mesh request, or
        ``"auto"``.  ``costs`` (stage -> seconds) seeds the profile the
        auto split balances on.  ``replicas`` (stage -> N >= 1) splits
        those stages' allocations into N replica submeshes (a fixed
        request then describes ONE replica); ``replica_min`` floors the
        counts the fit loop may shed to under device loss.  ``hosts``
        > 1 enables mesh mode: the pool partitions into per-host
        groups and every stage carves wholly inside one group --
        pinned by ``stage_hosts`` (stage -> host index, the placement
        block's ``host`` key) or filled greedily in declaration
        order."""
        if costs:
            for name, seconds in costs.items():
                self.record_cost(name, float(seconds))
        requests = self._normalize(stages)
        replicas = {name: max(1, int(count))
                    for name, count in (replicas or {}).items()
                    if name in requests}
        self._requests = requests
        self._replica_desired = dict(replicas)
        if replica_min is not None:
            self._replica_min = {name: max(1, int(count))
                                 for name, count in replica_min.items()}
        self.hosts = int(hosts) if hosts and int(hosts) > 1 else None
        self._stage_host_pins = {name: int(index) for name, index
                                 in (stage_hosts or {}).items()}
        self._carve(requests, replicas)
        return self.plans

    # -- mesh mode: per-host device groups ---------------------------------

    def _host_groups_for(self, devices: list) -> list[list]:
        """Partition ``devices`` into per-host groups: by the real
        ``process_index`` when a jax.distributed mesh spans processes,
        else ``self.hosts`` contiguous chunks of the topology-sorted
        pool (virtual hosts -- single-process reproduction of the
        multi-host carve, same code path)."""
        by_process: dict[int, list] = {}
        for device in devices:
            by_process.setdefault(
                int(getattr(device, "process_index", 0) or 0),
                []).append(device)
        if len(by_process) > 1:
            return [by_process[key] for key in sorted(by_process)]
        count = self.hosts or 1
        base, rem = divmod(len(devices), count)
        groups, pos = [], 0
        for index in range(count):
            size = base + (1 if index < rem else 0)
            groups.append(devices[pos:pos + size])
            pos += size
        return groups

    def stage_host(self, stage: str) -> int | None:
        """Which host group a stage is placed on (None outside mesh
        mode)."""
        return self.stage_hosts.get(stage) if self.hosts else None

    def same_host(self, stage_a: str, stage_b: str) -> bool:
        """True when a hop between the stages stays inside one host's
        ICI domain (always true outside mesh mode: one host)."""
        if not self.hosts:
            return True
        return self.stage_hosts.get(stage_a) \
            == self.stage_hosts.get(stage_b)

    def _carve(self, requests: dict, replicas: dict) -> None:
        """Cut the topology-sorted pool into per-stage chunks (and
        per-replica sub-chunks) for already-fitted requests; in mesh
        mode every chunk comes wholly from one host group."""
        resolved = self._resolve(requests, len(self.devices), replicas)
        self.plans = {}
        self.replica_plans = {}
        if self.hosts:
            self._carve_hosted(requests, replicas, resolved)
            return
        cursor = 0
        for name, axes in requests.items():
            total = resolved[name]
            chunk = self.devices[cursor:cursor + total]
            cursor += total
            self._place_chunk(name, axes, chunk, replicas)

    def _carve_hosted(self, requests: dict, replicas: dict,
                      resolved: dict) -> None:
        groups = self._host_groups_for(self.devices)
        self.host_groups = groups
        self.stage_hosts = {}
        cursors = [0] * len(groups)
        fill = 0
        for name, axes in requests.items():
            total = resolved[name]
            pin = self._stage_host_pins.get(name)
            if pin is not None:
                if not 0 <= pin < len(groups):
                    raise ValueError(
                        f"stage {name!r}: host {pin} out of range "
                        f"(mesh has {len(groups)} hosts)")
                if len(groups[pin]) - cursors[pin] < total:
                    raise ValueError(
                        f"stage {name!r} wants {total} chips on host "
                        f"{pin}, which has "
                        f"{len(groups[pin]) - cursors[pin]} free")
                host = pin
            else:
                host = None
                for offset in range(len(groups)):
                    candidate = (fill + offset) % len(groups)
                    if len(groups[candidate]) - cursors[candidate] \
                            >= total:
                        host = candidate
                        break
                if host is None:
                    raise ValueError(
                        f"stage {name!r} wants {total} chips but no "
                        f"host group has that many free (a stage "
                        f"never spans hosts -- its submesh must fit "
                        f"one ICI domain)")
                fill = host
            chunk = groups[host][cursors[host]:cursors[host] + total]
            cursors[host] += total
            self.stage_hosts[name] = host
            self._place_chunk(name, axes, chunk, replicas)

    def _place_chunk(self, name: str, axes, chunk: list,
                     replicas: dict) -> None:
        """Build a stage's MeshPlan (and replica sub-plans) from its
        carved device chunk -- shared by the flat and hosted carves."""
        total = len(chunk)
        if name in replicas:
            count = replicas[name]
            subs, pos = [], 0
            base, rem = divmod(total, count)
            for index in range(count):
                size = base + (1 if index < rem else 0)
                sub = chunk[pos:pos + size]
                pos += size
                sub_axes = dict(axes) if axes != "auto" \
                    else {"dp": size}
                subs.append(MeshPlan(make_mesh(sub_axes, sub)))
            self.replica_plans[name] = subs
            # The whole-stage plan (stage_devices, default hops,
            # stats) spans every replica's chips as one dp pool.
            self.plans[name] = MeshPlan(
                make_mesh({"dp": total}, chunk))
        else:
            plan_axes = dict(axes) if axes != "auto" \
                else {"dp": total}
            self.plans[name] = MeshPlan(make_mesh(plan_axes, chunk))

    def record_cost(self, stage: str, seconds: float) -> None:
        """EMA of the measured per-frame cost of a stage (fed from the
        engine's element spans); ``devices: auto`` splits re-balance on
        it at the next assign()/replace()."""
        prior = self.costs.get(stage)
        self.costs[stage] = float(seconds) if prior is None \
            else 0.75 * prior + 0.25 * float(seconds)

    def _fit(self, pool_size: int) -> tuple[dict, dict]:
        """Shrink the ORIGINAL requests (and desired replica counts)
        until they fit ``pool_size`` devices: replicated stages shed
        replicas first (graceful N-1 degradation, floored at
        ``replica_min``), then fixed stages halve their largest axis
        (power-of-two steps keep dp/tp/fsdp shardings valid)."""
        requests = {name: (axes if axes == "auto" else dict(axes))
                    for name, axes in self._requests.items()}
        replicas = dict(self._replica_desired)

        def need():
            total = 0
            for name, axes in requests.items():
                count = replicas.get(name, 1)
                if axes == "auto":
                    total += max(1, count)
                else:
                    total += int(np.prod(list(axes.values()))) * count
            return total

        def stage_need(name):
            axes = requests[name]
            count = replicas.get(name, 1)
            return count if axes == "auto" \
                else int(np.prod(list(axes.values()))) * count

        while need() > pool_size:
            sheddable = [name for name, count in replicas.items()
                         if count > self._replica_min.get(name, 1)]
            if sheddable:
                name = max(sheddable, key=stage_need)
                replicas[name] -= 1
                continue
            shrinkable = [name for name, axes in requests.items()
                          if axes != "auto"
                          and int(np.prod(list(axes.values()))) > 1]
            if not shrinkable:
                raise RuntimeError(
                    f"cannot shrink stages below one device "
                    f"({pool_size} survivors for "
                    f"{len(requests)} stages)")
            name = max(shrinkable,
                       key=lambda n: int(np.prod(
                           list(requests[n].values()))))
            axes = requests[name]
            axis = max(axes, key=axes.get)
            axes[axis] = max(1, axes[axis] // 2)
        return requests, replicas

    def replace(self, failed_devices: Sequence) -> dict[str, MeshPlan]:
        """Re-place every stage onto the surviving devices (SURVEY.md
        §5.3 TPU-equiv: re-shard onto surviving chips).

        Failed devices leave the pool permanently (survivors keep their
        topology-sorted order, so chunks stay ICI-contiguous);
        replicated stages shed replicas first (down to ``replica_min``),
        then fixed stage requests shrink by halving their largest axis
        (power-of-two steps keep dp/tp/fsdp shardings valid) until the
        total fits, and ``auto`` stages re-split the remaining pool by
        recorded cost.  Plans are rebuilt in place -- elements must drop
        cached plans and re-put weights
        (``TPUElement.on_replacement``)."""
        failed = set(failed_devices)
        survivors = [d for d in self.devices if d not in failed]
        if len(survivors) == len(self.devices):
            return self.plans
        if not survivors:
            raise RuntimeError("no surviving devices to re-place onto")
        requests, replicas = self._fit(len(survivors))
        self.devices = survivors
        self._shardings.clear()
        self.generation += 1
        self.replica_epoch += 1
        self._carve(requests, replicas)
        return self.plans

    def reassign(self) -> dict[str, MeshPlan]:
        """Re-fit the ORIGINAL requests (desired replica counts
        included) onto the current pool and re-carve every stage: the
        background rebuild of a dropped replica, and the autoscaler's
        re-split after ``set_replicas``.  Bumps the generation --
        callers must invalidate plans/frames exactly as after
        ``replace()``."""
        requests, replicas = self._fit(len(self.devices))
        self._shardings.clear()
        self.generation += 1
        self.replica_epoch += 1
        self._carve(requests, replicas)
        return self.plans

    def plan(self, stage: str) -> MeshPlan:
        return self.plans[stage]

    # -- replicated stages -------------------------------------------------

    @property
    def has_replicas(self) -> bool:
        return bool(self.replica_plans)

    def replica_total(self, stage: str) -> int:
        """Slots (live or dead) of a replicated stage; 0 when the stage
        is not replicated."""
        return len(self.replica_plans.get(stage, ()))

    def live_replicas(self, stage: str) -> list[int]:
        return [index for index, plan
                in enumerate(self.replica_plans.get(stage, ()))
                if plan is not None]

    def replica_plan(self, stage: str, index: int) -> MeshPlan:
        plan = self.replica_plans[stage][index]
        if plan is None:
            raise KeyError(f"stage {stage!r} replica {index} is dead")
        return plan

    def replica_devices(self, stage: str, index: int) -> set:
        plans = self.replica_plans.get(stage, ())
        if index >= len(plans) or plans[index] is None:
            return set()
        return set(plans[index].mesh.devices.flat)

    def replica_of(self, stage: str, device) -> int | None:
        """Which live replica of ``stage`` owns ``device`` (None when
        the stage is not replicated or the device is not placed
        there)."""
        for index, plan in enumerate(self.replica_plans.get(stage, ())):
            if plan is not None and device in set(plan.mesh.devices.flat):
                return index
        return None

    def set_replicas(self, stage: str, count: int) -> None:
        """Update a replicated stage's DESIRED count (the autoscaler's
        knob); takes effect at the next ``reassign()``."""
        if stage not in self._replica_desired:
            raise KeyError(f"stage {stage!r} is not replicated")
        self._replica_desired[stage] = max(
            self._replica_min.get(stage, 1), int(count))

    def drop_replica(self, stage: str, index: int) -> set:
        """Retire ONE replica's chips (peer-shedding failover): the
        devices leave the pool permanently, the slot reads dead, and --
        the point -- no other submesh is touched: peers keep serving on
        their exact meshes, so ``generation`` does NOT bump (only
        ``replica_epoch``, which invalidates per-replica plan caches
        and this stage's memoized shardings).  Returns the retired
        device set (empty when the slot is unknown/already dead)."""
        subs = self.replica_plans.get(stage)
        if not subs or index >= len(subs) or subs[index] is None:
            return set()
        dead = set(subs[index].mesh.devices.flat)
        subs[index] = None
        self.devices = [d for d in self.devices if d not in dead]
        alive = [d for plan in subs if plan is not None
                 for d in plan.mesh.devices.flat]
        if alive:
            self.plans[stage] = MeshPlan(
                make_mesh({"dp": len(alive)}, alive))
        else:
            self.plans.pop(stage, None)
        self.replica_epoch += 1
        self._shardings = {key: value
                           for key, value in self._shardings.items()
                           if key[0] != stage}
        return dead

    def stage_devices(self, stage: str) -> set:
        """The devices a stage's submesh currently occupies (empty for
        an unknown stage) -- the chaos harness's ``device_kill`` target
        resolution and the replay path's blast-radius checks."""
        plan = self.plans.get(stage)
        if plan is None:
            return set()
        return set(plan.mesh.devices.flat)

    # -- stage hops --------------------------------------------------------

    def stage_sharding(self, stage: str, spec: tuple = (),
                       replica: int | None = None) -> NamedSharding:
        """The memoized NamedSharding frames reshard onto when hopping
        to ``stage`` (or one replica's submesh of it) -- built once per
        (stage, replica, generation, spec), not per frame."""
        key = (stage, replica, self.generation,
               tuple(spec) if spec else None)
        sharding = self._shardings.get(key)
        if sharding is None:
            plan = self.plans[stage] if replica is None \
                else self.replica_plan(stage, replica)
            sharding = plan.shard(*spec) if spec else plan.replicated()
            self._shardings[key] = sharding
        return sharding

    def transfer(self, value, to_stage: str, *spec,
                 replica: int | None = None):
        """Reshard ``value`` (array or pytree) onto a stage's mesh (a
        single replica's submesh when ``replica`` is given).

        Non-blocking: ``jax.device_put`` dispatches the ICI copy and
        returns immediately, so the hop overlaps the upstream stage's
        next-frame compute.  Leaves whose committed sharding already IS
        the target sharding pass through untouched (kills the per-frame
        no-op device_put walk for values resident on the stage)."""
        sharding = self.stage_sharding(to_stage, spec, replica=replica)

        def hop(leaf):
            if not hasattr(leaf, "shape"):
                return leaf
            if getattr(leaf, "sharding", None) == sharding:
                self.transfer_skipped += 1
                return leaf
            self.transfer_puts += 1
            return jax.device_put(leaf, sharding)

        return jax.tree_util.tree_map(hop, value)

    @property
    def stats(self) -> dict:
        result = {"generation": self.generation,
                  "stages": {name: int(plan.mesh.devices.size)
                             for name, plan in self.plans.items()},
                  "costs_ms": {name: round(cost * 1000.0, 3)
                               for name, cost in self.costs.items()},
                  "transfer_puts": self.transfer_puts,
                  "transfer_skipped": self.transfer_skipped,
                  "shardings_cached": len(self._shardings)}
        if self.replica_plans:
            result["replica_epoch"] = self.replica_epoch
            result["replicas"] = {
                name: [None if plan is None
                       else int(plan.mesh.devices.size)
                       for plan in plans]
                for name, plans in self.replica_plans.items()}
        if self.hosts:
            result["hosts"] = self.hosts
            result["host_groups"] = [len(group)
                                     for group in self.host_groups]
            result["stage_hosts"] = dict(self.stage_hosts)
        return result


def tree_device_put(tree, plan: MeshPlan, spec: P | None = None):
    """device_put every array leaf of a swag/pytree onto ``plan``."""
    sharding = plan.shard(spec) if spec is not None else plan.replicated()
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, sharding)
        if hasattr(leaf, "shape") else leaf, tree)


# ---------------------------------------------------------------------------
# Host-side array codec (only for frames leaving the process).

def encode_array(array) -> bytes:
    """jax/numpy array -> self-describing bytes (npy format)."""
    buffer = io.BytesIO()
    np.save(buffer, np.asarray(array), allow_pickle=False)
    return buffer.getvalue()


def decode_array(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


# ---------------------------------------------------------------------------
# TPU element base class.

# Sentinel for TPUElement's not-yet-computed placement-stage cache
# (None is a valid resolved value: "unplaced").
_UNRESOLVED = object()


class TPUElement(PipelineElement):
    """PipelineElement hosting jitted computation on a device mesh.

    Placement resolves from the ``placement`` parameter: ``"local"``
    (all local devices, default), a mesh request like
    ``{"dp": 2, "tp": 4}``, or a stage name previously assigned on the
    pipeline's StagePlacement.  Subclasses use ``self.jit`` for
    shape-keyed compiled caches and ``self.plan`` for shardings.

    TPU elements are ``device_resident``: outputs may stay un-synced
    ``jax.Array`` (the engine only syncs at sinks / the bounded dispatch
    window), and event-loop execution runs under the pipeline's
    transfer guard (pipeline/overlap.py).  The ``donation-alias`` lint
    rule (analysis/residency.py) keys off this attribute at ``pipeline
    create``: a graph mapping that reads a producer-qualified alias of
    a device output another element overwrites pins the buffer and
    blocks HBM donation for any fused segment containing it.
    """

    device_resident = True

    def __init__(self, context):
        super().__init__(context)
        self._plan: MeshPlan | None = None
        self._replica_plan_cache: dict = {}
        self._stage_name_cache = _UNRESOLVED
        self.jit_cache = JitCache()
        self.bucketer = ShapeBucketer()

    @property
    def plan(self) -> MeshPlan:
        # Replicated stages (ISSUE 7): while a stage worker executes
        # this element for a specific replica, ``self.plan`` IS that
        # replica's submesh -- an element-side put/shard lands on the
        # replica's chips, never on a peer's (or a dead slot's).  The
        # cache keys on the placement's replica_epoch so a
        # drop/reassign invalidates it without a full on_replacement.
        pipeline = self.pipeline
        placements = getattr(pipeline, "stage_placement", None)
        current = getattr(pipeline, "current_replica", None)
        context = current() if callable(current) else None
        if context is not None and placements is not None:
            stage, index = context
            if stage in placements.replica_plans \
                    and self._placement_stage() == stage:
                key = (stage, index, placements.generation,
                       placements.replica_epoch)
                plan = self._replica_plan_cache.get(key)
                if plan is None:
                    plan = placements.replica_plan(stage, index)
                    self._replica_plan_cache = {key: plan}
                return plan
        if self._plan is None:
            self._plan = self._resolve_placement()
        return self._plan

    def _placement_stage(self) -> str | None:
        """The placed-stage name this element's placement resolves to
        (None when unplaced) -- same lookup order as
        ``_resolve_placement``.  Cached: ``self.plan`` consults it on
        every access in the replica worker hot path, and the binding is
        structural (definition placement block / parameter), not
        per-frame.  Cleared by ``on_replacement``."""
        if self._stage_name_cache is not _UNRESOLVED:
            return self._stage_name_cache
        placements = getattr(self.pipeline, "stage_placement", None)
        if placements is None:
            return None                 # no placement yet: don't cache
        placement, _ = self.get_parameter("placement", "local")
        name = None
        for key in (placement, self.name):
            if isinstance(key, str) and (
                    key in placements.plans
                    or key in placements.replica_plans):
                name = key
                break
        self._stage_name_cache = name
        return name

    def _resolve_placement(self) -> MeshPlan:
        placement, _ = self.get_parameter("placement", "local")
        placements = getattr(self.pipeline, "stage_placement", None)
        if placements is not None:
            # A definition ``placement`` block registers the stage under
            # the element's own node name; the ``placement`` parameter
            # may also name another stage explicitly (shared submesh).
            for key in (placement, self.name):
                if isinstance(key, str) and key in placements.plans:
                    return placements.plan(key)
        # Device pool: the StagePlacement's (which excludes chips removed
        # by replace()) when one exists, else all local devices -- a
        # default-placed element must never re-resolve onto a dead chip.
        pool = list(placements.devices) if placements is not None \
            else list(jax.devices())
        if isinstance(placement, dict):
            axes = dict(placement)
            sizes = list(axes.values())
            if -1 not in sizes and int(np.prod(sizes)) <= len(pool):
                return MeshPlan(make_mesh(axes,
                                          pool[:int(np.prod(sizes))]))
            return MeshPlan(make_mesh(axes, pool))
        return MeshPlan(make_mesh({"dp": len(pool)}, pool))

    def jit(self, fn: Callable) -> Callable:
        """Shape-keyed compiled cache for this element."""
        return self.jit_cache(fn)

    def on_replacement(self):
        """Devices were re-placed under this element (chip failure ->
        ``StagePlacement.replace``): drop the cached plan and compiled
        functions so the next frame resolves the new submesh and
        recompiles there.  Model-hosting subclasses also drop their
        resident weights, which rebuild lazily -- from the
        ``checkpoint`` parameter when set, so recovery restores real
        weights, not random init."""
        self._plan = None
        self._replica_plan_cache = {}
        self._stage_name_cache = _UNRESOLVED
        self.jit_cache = JitCache()

    def put(self, value, *spec):
        """Place an array (or pytree) on this element's mesh."""
        sharding = (self.plan.shard(*spec) if spec
                    else self.plan.replicated())
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding)
            if hasattr(leaf, "shape") else leaf, value)

    def metrics(self) -> dict:
        return {"jit": self.jit_cache.stats,
                "mesh": dict(self.plan.mesh.shape)}
