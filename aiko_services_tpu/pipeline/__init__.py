from .stream import (Stream, Frame, StreamEvent, StreamState,
                     DEFAULT_STREAM_ID, FIRST_FRAME_ID)
from .definition import (PipelineDefinition, ElementDefinition,
                         DefinitionError, parse_pipeline_definition,
                         load_pipeline_definition)
from .element import PipelineElement, PipelineElementLoop, ElementContext
from .pipeline import Pipeline, RemoteStage, PROTOCOL_PIPELINE, \
    create_pipeline
from .scheme import DataScheme, DataSource, DataTarget, contains_all
from .codec import (encode_frame_data, decode_frame_data, encode_value,
                    decode_value)
from .journal import (StreamJournal, JournalState, load_journal,
                      claim_adoption, adopter_of)
from .overlap import TransferLedger, DeviceWindow, device_leaves
from .fusion import (DeviceFn, FusedSegment, FusionError, FUSE_MODES,
                     setup_compilation_cache)
from .tensor import (TPUElement, JitCache, ShapeBucketer, StagePlacement,
                     encode_array, decode_array, tree_device_put)
