"""DataScheme registry + DataSource/DataTarget element bases (reference:
src/aiko_services/main/scheme.py:12-62, source_target.py:30-108).

A DataScheme handles a URL scheme (``file://``, ``tty://``, ``tcp://``...)
for source/target elements: ``create_sources`` turns ``data_sources``
parameters into frames (one-shot or generator), ``create_targets``
prepares writers.
"""

from __future__ import annotations

from typing import Callable

from .element import PipelineElement
from .stream import Stream, StreamEvent
from ..utils import get_logger

__all__ = ["DataScheme", "DataSource", "DataTarget", "contains_all"]

_logger = get_logger("aiko.scheme")


def contains_all(source: str, fragments) -> bool:
    return all(fragment in source for fragment in fragments)


class DataScheme:
    _registry: dict[str, type] = {}

    def __init__(self, element: PipelineElement):
        self.element = element

    @classmethod
    def register(cls, scheme_name: str):
        def decorator(scheme_cls):
            cls._registry[scheme_name] = scheme_cls
            return scheme_cls
        return decorator

    @classmethod
    def lookup(cls, scheme_name: str) -> type | None:
        return cls._registry.get(scheme_name)

    @staticmethod
    def parse_data_url_scheme(data_url: str) -> str:
        if "://" not in data_url:
            return "file"
        return data_url.split("://", 1)[0].lower()

    @staticmethod
    def parse_data_url_path(data_url: str) -> str:
        if "://" not in data_url:
            return data_url
        return data_url.split("://", 1)[1]

    # -- to implement ------------------------------------------------------

    def create_sources(self, stream: Stream, data_sources: list[str],
                       frame_generator: Callable | None = None,
                       rate: float | None = None):
        raise NotImplementedError

    def create_targets(self, stream: Stream, data_targets: list[str]):
        raise NotImplementedError

    def destroy_sources(self, stream: Stream):
        pass

    def destroy_targets(self, stream: Stream):
        pass


class _SchemeBound(PipelineElement):
    PARAMETER: str = ""
    CREATE: str = ""
    DESTROY: str = ""

    def __init__(self, context):
        super().__init__(context)
        self._schemes: dict[str, DataScheme] = {}

    def _resolve(self, stream: Stream) -> tuple[list[str], DataScheme]:
        value, found = self.get_parameter(self.PARAMETER)
        if not found or not value:
            raise ValueError(f"{self.name}: parameter "
                             f"{self.PARAMETER!r} not set")
        urls = value if isinstance(value, list) else [value]
        scheme_name = DataScheme.parse_data_url_scheme(urls[0])
        scheme_cls = DataScheme.lookup(scheme_name)
        if scheme_cls is None:
            raise ValueError(f"{self.name}: no DataScheme for "
                             f"{scheme_name!r}")
        scheme = scheme_cls(self)
        self._schemes[stream.stream_id] = scheme
        return urls, scheme

    def stop_stream(self, stream: Stream, stream_id):
        scheme = self._schemes.pop(stream.stream_id, None)
        if scheme is not None:
            getattr(scheme, self.DESTROY)(stream)
        return StreamEvent.OKAY, {}


class DataSource(_SchemeBound):
    """Element base: resolves ``data_sources`` to a scheme at stream start
    and pumps frames (reference source_target.py:30-72)."""

    PARAMETER = "data_sources"
    DESTROY = "destroy_sources"

    def start_stream(self, stream: Stream, stream_id):
        try:
            urls, scheme = self._resolve(stream)
        except ValueError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        rate, _ = self.get_parameter("rate", None)
        rate = float(rate) if rate else None
        # Pass a generator only when the subclass provides one; otherwise
        # the scheme supplies its own (e.g. one frame per matched file).
        generator = None
        if type(self).frame_generator is not DataSource.frame_generator:
            generator = self.frame_generator
        return scheme.create_sources(
            stream, urls, frame_generator=generator, rate=rate) \
            or (StreamEvent.OKAY, {})

    def frame_generator(self, stream: Stream):
        """Subclasses may override: produce (StreamEvent, frame_data)."""
        return StreamEvent.STOP, {}

    def process_frame(self, stream: Stream, **inputs):
        # Sources pass data through once frames are created by the scheme.
        return StreamEvent.OKAY, inputs


class DataTarget(_SchemeBound):
    """Element base: resolves ``data_targets`` at stream start (reference
    source_target.py:74-108)."""

    PARAMETER = "data_targets"
    DESTROY = "destroy_targets"

    def start_stream(self, stream: Stream, stream_id):
        try:
            urls, scheme = self._resolve(stream)
        except ValueError as error:
            return StreamEvent.ERROR, {"diagnostic": str(error)}
        return scheme.create_targets(stream, urls) \
            or (StreamEvent.OKAY, {})

    def scheme_for(self, stream: Stream) -> DataScheme | None:
        return self._schemes.get(stream.stream_id)
