"""Stage-parallel execution over placed submeshes (ISSUE 3 tentpole).

PR 1 overlapped frames within a stream and PR 2 fused device chains into
single dispatches, but a multi-stage *placed* pipeline (``placement:``
blocks -> :class:`~.tensor.StagePlacement` submeshes) still walked every
frame stage-by-stage on one event-loop turn: while frame k occupied the
LLM stage's chips, the detect stage's chips idled.  Profiled model
segmentation across multi-TPU systems (arXiv:2503.01025) and
topology-aware auto-parallel placement (AoiZora, arXiv:2606.17566) both
identify stage balance + inter-stage hop locality as where the remaining
end-to-end throughput lives.  This module makes placed stages execute
like a hardware pipeline:

- :class:`StageScheduler` keeps a **credit-based admission window per
  placed stage** (the stage-keyed analogue of PR 1's per-stream
  ``DeviceWindow``; ``stage_inflight`` pipeline parameter, default
  depth 2).  A frame admits into a stage before running its head
  element, holds the credit until the NEXT stage admits it (so a full
  downstream window backpressures upstream admissions, exactly like
  pipeline stall propagation in hardware), and frames denied admission
  queue FIFO and resume when a credit frees.  Admission happens on a
  fresh mailbox turn, so frame k+1's upstream stage work interleaves
  with frame k's downstream stage on the same event loop.
- :class:`StageExecutor` gives each placed stage **one FIFO worker
  thread**: synchronous stage-head elements (and stage-local fused
  segments) execute there instead of on the event loop, parking the
  frame like an async element and resuming through the mailbox.  While
  frame k blocks on the LLM submesh's result, the event loop is free to
  walk frame k+1 onto the detect submesh -- cross-stage pipelining of
  plain synchronous elements, with per-stream order preserved by the
  FIFO queue.  Async elements keep their own admission discipline
  (MicroBatcher/ContinuousBatcher); the engine releases the stage
  credit when a frame parks at one.
- Per-stage **occupancy accounting** (busy-time integration over a
  resettable window) feeds the ``stage_occupancy_*`` bench keys and the
  profiler's ``stage:`` spans -- the direct evidence that two stages
  ran concurrently.

Scope note: stage credits are held in graph-path order and released
forward, so admission is deadlock-free on acyclic paths.  A Loop element
that jumps BACK across two placed stages while both windows are full
could stall; placed stages inside loop bodies should size
``stage_inflight`` above the loop's frame concurrency.

In-order delivery: stage-parallel frames complete out of walk order
(async stages, per-stage workers), so the engine assigns every ingested
frame a per-stream delivery sequence and buffers responses until all
predecessors responded (``Pipeline._deliver``) -- callers see ingest
order, always.
"""

from __future__ import annotations

import time

from collections import deque
from concurrent.futures import ThreadPoolExecutor

from ..utils import get_logger

__all__ = ["StageScheduler", "StageExecutor", "STAGE_INFLIGHT_DEFAULT",
           "STAGE_PIPELINE_MODES"]

_logger = get_logger("aiko.stages")

# Default per-stage admission window (double buffering: one frame
# executing on the stage's submesh, one hopping/queued behind it).
# Override with the ``stage_inflight`` pipeline parameter.
STAGE_INFLIGHT_DEFAULT = 2

STAGE_PIPELINE_MODES = ("auto", "off")


class StageExecutor:
    """One FIFO worker thread for one placed stage (a thin wrapper over
    ``ThreadPoolExecutor(max_workers=1)``).

    Jobs are closures the engine builds (element call or fused-segment
    dispatch + a mailbox post of the continuation); the single thread
    serializes a stage's execution -- per-stream order through the stage
    is the queue order -- while different stages' threads run
    concurrently, which is what lets synchronous placed stages overlap
    in wall time."""

    def __init__(self, name: str):
        self.name = name
        self.executed = 0
        self._stopped = False
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"stage-{name}")

    def submit(self, job) -> None:
        if self._stopped:       # teardown: streams are already gone
            return
        self._pool.submit(self._run, job)

    def _run(self, job) -> None:
        try:
            job()
        except Exception:           # jobs carry their own error path;
            _logger.exception(      # this is the backstop
                "stage %s: worker job raised", self.name)
        self.executed += 1

    def stall(self, seconds: float) -> None:
        """Chaos harness (``stage_stall`` fault point): occupy the FIFO
        worker for ``seconds`` -- every job queued behind it waits,
        exactly like a stage whose chips went quiet mid-stream.  Rides
        the normal queue, so ordering invariants still hold."""
        delay = float(seconds)
        _logger.warning("stage %s: injected %.0f ms worker stall",
                        self.name, delay * 1000.0)
        self.submit(lambda: time.sleep(delay))

    def stop(self):
        self._stopped = True
        self._pool.shutdown(wait=False)


class StageScheduler:
    """Credit-based per-stage admission + occupancy accounting.

    Owned by the event loop: every method except the workers' own job
    bodies runs on the pipeline's actor thread, so no locking.  The
    waiter tokens are opaque ``(stream_id, frame_id, node_name)``
    triples the engine re-posts as ``enter_stage_frame`` continuations.
    """

    def __init__(self, stages, depth: int = STAGE_INFLIGHT_DEFAULT):
        self.depth = max(1, int(depth))
        self.stages = list(stages)
        self._active: dict[str, int] = {s: 0 for s in self.stages}
        self._waiters: dict[str, deque] = {s: deque() for s in self.stages}
        # Credits promised to POPPED waiter tokens whose resume posts
        # are still in the mailbox: fresh admissions must not steal
        # them, or a later frame overtakes an earlier one through the
        # stage (the reorder buffer would still order the RESPONSES,
        # but a stateful stage element would see frames out of order).
        self._reserved: dict[str, int] = {s: 0 for s in self.stages}
        self._executors: dict[str, StageExecutor] = {}
        # Occupancy: integrate the time each stage has >= 1 admitted
        # frame, over a resettable window (bench resets at the start of
        # its timed pass).
        self._busy: dict[str, float] = {s: 0.0 for s in self.stages}
        self._busy_since: dict[str, float | None] = \
            {s: None for s in self.stages}
        self._window_start = time.monotonic()
        self.admitted: dict[str, int] = {s: 0 for s in self.stages}
        self.queued: dict[str, int] = {s: 0 for s in self.stages}

    # -- workers -----------------------------------------------------------

    def executor(self, stage: str) -> StageExecutor:
        worker = self._executors.get(stage)
        if worker is None:
            worker = self._executors[stage] = StageExecutor(stage)
        return worker

    # -- admission window --------------------------------------------------

    def try_admit(self, stage: str, reserved: bool = False) -> bool:
        """``reserved`` marks the admission attempt of a popped waiter
        token, which consumes its reservation; a fresh attempt may only
        take capacity BEYOND the outstanding reservations (the reserved
        credits belong to earlier queued frames), but genuinely free
        surplus stays usable."""
        if reserved:
            self.cancel_reservation(stage)
        elif self._active.get(stage, 0) \
                + self._reserved.get(stage, 0) >= self.depth:
            return False
        if self._active.get(stage, 0) >= self.depth:
            return False
        self._active[stage] = self._active.get(stage, 0) + 1
        self.admitted[stage] = self.admitted.get(stage, 0) + 1
        if self._active[stage] == 1:
            self._busy_since[stage] = time.monotonic()
        return True

    def cancel_reservation(self, stage: str) -> None:
        if self._reserved.get(stage, 0) > 0:
            self._reserved[stage] -= 1

    def enqueue(self, stage: str, token, front: bool = False) -> None:
        """FIFO wait queue for a full stage; ``front`` requeues a token
        whose freed credit was stolen by an interleaving admission, so
        queue order (and per-stream frame order) is preserved."""
        waiters = self._waiters.setdefault(stage, deque())
        if front:
            waiters.appendleft(token)
        else:
            self.queued[stage] = self.queued.get(stage, 0) + 1
            waiters.append(token)

    def release(self, stage: str):
        """Return one credit; returns the next waiter token to resume
        (or None)."""
        if self._active.get(stage, 0) > 0:
            self._active[stage] -= 1
            if self._active[stage] == 0 \
                    and self._busy_since.get(stage) is not None:
                self._busy[stage] = self._busy.get(stage, 0.0) + \
                    time.monotonic() - self._busy_since[stage]
                self._busy_since[stage] = None
        return self.next_waiter(stage)

    def next_waiter(self, stage: str):
        """Pop the next waiter when an unreserved credit is available
        (used both on release and when a popped waiter turned out
        dead); the popped token takes a reservation on that credit
        until its admission post lands."""
        waiters = self._waiters.get(stage)
        if waiters and self._active.get(stage, 0) \
                + self._reserved.get(stage, 0) < self.depth:
            self._reserved[stage] = self._reserved.get(stage, 0) + 1
            return waiters.popleft()
        return None

    def waiting(self, stage: str) -> int:
        return len(self._waiters.get(stage, ()))

    def active(self, stage: str) -> int:
        return self._active.get(stage, 0)

    # -- occupancy ---------------------------------------------------------

    def reset_window(self) -> None:
        now = time.monotonic()
        for stage in self.stages:
            self._busy[stage] = 0.0
            if self._busy_since.get(stage) is not None:
                self._busy_since[stage] = now
        self._window_start = now

    def occupancy(self, stage: str) -> float:
        wall = time.monotonic() - self._window_start
        if wall <= 0:
            return 0.0
        busy = self._busy.get(stage, 0.0)
        if self._busy_since.get(stage) is not None:
            busy += time.monotonic() - self._busy_since[stage]
        return min(1.0, busy / wall)

    # -- reporting ---------------------------------------------------------

    @property
    def stats(self) -> dict:
        return {stage: {"active": self._active.get(stage, 0),
                        "admitted": self.admitted.get(stage, 0),
                        "queued": self.queued.get(stage, 0),
                        "waiting": self.waiting(stage),
                        "reserved": self._reserved.get(stage, 0),
                        "depth": self.depth,
                        # Worker jobs the stage's executor completed --
                        # with "admitted" this localizes a stall to
                        # admission (credits) vs execution (worker).
                        "executed": self._executors[stage].executed
                        if stage in self._executors else 0,
                        "occupancy": round(self.occupancy(stage), 4)}
                for stage in self.stages}

    def stop(self):
        for worker in self._executors.values():
            worker.stop()
        self._executors.clear()
