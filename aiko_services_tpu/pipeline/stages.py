"""Stage-parallel execution over placed submeshes (ISSUE 3 tentpole).

PR 1 overlapped frames within a stream and PR 2 fused device chains into
single dispatches, but a multi-stage *placed* pipeline (``placement:``
blocks -> :class:`~.tensor.StagePlacement` submeshes) still walked every
frame stage-by-stage on one event-loop turn: while frame k occupied the
LLM stage's chips, the detect stage's chips idled.  Profiled model
segmentation across multi-TPU systems (arXiv:2503.01025) and
topology-aware auto-parallel placement (AoiZora, arXiv:2606.17566) both
identify stage balance + inter-stage hop locality as where the remaining
end-to-end throughput lives.  This module makes placed stages execute
like a hardware pipeline:

- :class:`StageScheduler` keeps a **credit-based admission window per
  placed stage** (the stage-keyed analogue of PR 1's per-stream
  ``DeviceWindow``; ``stage_inflight`` pipeline parameter, default
  depth 2).  A frame admits into a stage before running its head
  element, holds the credit until the NEXT stage admits it (so a full
  downstream window backpressures upstream admissions, exactly like
  pipeline stall propagation in hardware), and frames denied admission
  queue FIFO and resume when a credit frees.  Admission happens on a
  fresh mailbox turn, so frame k+1's upstream stage work interleaves
  with frame k's downstream stage on the same event loop.
- :class:`StageExecutor` gives each placed stage **one FIFO worker
  thread**: synchronous stage-head elements (and stage-local fused
  segments) execute there instead of on the event loop, parking the
  frame like an async element and resuming through the mailbox.  While
  frame k blocks on the LLM submesh's result, the event loop is free to
  walk frame k+1 onto the detect submesh -- cross-stage pipelining of
  plain synchronous elements, with per-stream order preserved by the
  FIFO queue.  Async elements keep their own admission discipline
  (MicroBatcher/ContinuousBatcher); the engine releases the stage
  credit when a frame parks at one.
- Per-stage **occupancy accounting** (busy-time integration over a
  resettable window) feeds the ``stage_occupancy_*`` bench keys and the
  profiler's ``stage:`` spans -- the direct evidence that two stages
  ran concurrently.
- :class:`ReplicaGroup` (ISSUE 7) generalizes the admission window for
  **replicated stages** (``placement: {..., replicas: N}``): N
  data-parallel replica submeshes each get their own credit window and
  FIFO worker, frames round-robin across the live replicas, and the
  reorder buffer merges completions back to ingest order.  A dead
  replica stops admitting and its in-flight frames shed to the peers
  (the engine's ``fail_replica`` replay path); a rebuilt replica
  re-admits half-open behind a single canary frame, breaker-style.

Scope note: stage credits are held in graph-path order and released
forward, so admission is deadlock-free on acyclic paths.  A Loop element
that jumps BACK across two placed stages while both windows are full
could stall; placed stages inside loop bodies should size
``stage_inflight`` above the loop's frame concurrency.

In-order delivery: stage-parallel frames complete out of walk order
(async stages, per-stage workers), so the engine assigns every ingested
frame a per-stream delivery sequence and buffers responses until all
predecessors responded (``Pipeline._deliver``) -- callers see ingest
order, always.
"""

from __future__ import annotations

import time

from collections import deque
from concurrent.futures import ThreadPoolExecutor

from ..utils import get_logger

__all__ = ["ReplicaGroup", "StageScheduler", "StageExecutor",
           "STAGE_INFLIGHT_DEFAULT", "STAGE_PIPELINE_MODES",
           "REPLICA_LIVE", "REPLICA_DEAD", "REPLICA_HALF_OPEN"]

_logger = get_logger("aiko.stages")

# Replica slot states (ISSUE 7).  ``half_open`` is the breaker-style
# canary state a rebuilt replica re-admits through: exactly ONE frame
# is admitted; its success closes the slot to ``live``, its failure
# re-kills it.
REPLICA_LIVE = "live"
REPLICA_DEAD = "dead"
REPLICA_HALF_OPEN = "half_open"

# Default per-stage admission window (double buffering: one frame
# executing on the stage's submesh, one hopping/queued behind it).
# Override with the ``stage_inflight`` pipeline parameter.
STAGE_INFLIGHT_DEFAULT = 2

STAGE_PIPELINE_MODES = ("auto", "off")


class StageExecutor:
    """One FIFO worker thread for one placed stage (a thin wrapper over
    ``ThreadPoolExecutor(max_workers=1)``).

    Jobs are closures the engine builds (element call or fused-segment
    dispatch + a mailbox post of the continuation); the single thread
    serializes a stage's execution -- per-stream order through the stage
    is the queue order -- while different stages' threads run
    concurrently, which is what lets synchronous placed stages overlap
    in wall time."""

    def __init__(self, name: str):
        self.name = name
        self.executed = 0
        self._stopped = False
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"stage-{name}")

    def submit(self, job) -> None:
        if self._stopped:       # teardown: streams are already gone
            return
        self._pool.submit(self._run, job)

    def _run(self, job) -> None:
        try:
            job()
        except Exception:           # jobs carry their own error path;
            _logger.exception(      # this is the backstop
                "stage %s: worker job raised", self.name)
        self.executed += 1

    def stall(self, seconds: float) -> None:
        """Chaos harness (``stage_stall`` fault point): occupy the FIFO
        worker for ``seconds`` -- every job queued behind it waits,
        exactly like a stage whose chips went quiet mid-stream.  Rides
        the normal queue, so ordering invariants still hold."""
        delay = float(seconds)
        _logger.warning("stage %s: injected %.0f ms worker stall",
                        self.name, delay * 1000.0)
        self.submit(lambda: time.sleep(delay))

    def stop(self):
        self._stopped = True
        self._pool.shutdown(wait=False)


class ReplicaGroup:
    """Admission state for one replicated stage (ISSUE 7): a credit
    window PER replica, round-robin admission across the live slots,
    and the dead / half-open (canary) lifecycle the failover and
    rebuild paths drive.

    Owned by the event loop like the scheduler -- no locking.  The
    group only decides WHICH replica admits a frame; the stage-level
    FIFO wait queue, reservations and backpressure stay in
    :class:`StageScheduler` (a queued frame wakes when ANY replica
    frees a credit, so the queue cannot strand behind a dead slot)."""

    def __init__(self, stage: str, count: int,
                 depth: int = STAGE_INFLIGHT_DEFAULT):
        self.stage = stage
        self.depth = max(1, int(depth))
        self.states: list[str] = [REPLICA_LIVE] * max(1, int(count))
        self.active: list[int] = [0] * len(self.states)
        self.admitted: list[int] = [0] * len(self.states)
        self._rr = 0                    # round-robin cursor
        self.failovers = 0
        self.rebuilds = 0
        self.canary_inflight: list[bool] = [False] * len(self.states)
        # Per-replica busy-time integration (same windowed discipline
        # as the scheduler's per-stage occupancy).
        self._busy: list[float] = [0.0] * len(self.states)
        self._busy_since: list[float | None] = [None] * len(self.states)
        self._window_start = time.monotonic()
        self.transitions: list[tuple] = []   # (slot, state, monotonic)

    # -- admission ---------------------------------------------------------

    def free_slots(self) -> int:
        """Credits currently grantable across live slots (a half-open
        slot counts at most its single canary)."""
        free = 0
        for index, state in enumerate(self.states):
            if state == REPLICA_LIVE:
                free += max(0, self.depth - self.active[index])
            elif state == REPLICA_HALF_OPEN \
                    and not self.canary_inflight[index] \
                    and self.active[index] == 0:
                free += 1
        return free

    def pick(self, least_loaded: bool = False) -> int | None:
        """Next replica to admit into (round-robin over live slots with
        a free credit; a half-open slot admits exactly one canary), or
        None when every slot is full/dead.  ``least_loaded`` (the QoS
        plane, ISSUE 12: latency-sensitive classes) picks the live
        slot with the fewest in-flight frames instead of the cursor's
        next -- head-of-line latency over round-robin fairness; the
        canary discipline is unchanged (a half-open slot only ever
        admits through the round-robin walk below)."""
        count = len(self.states)
        if least_loaded:
            # A canary-ready half-open slot is probed FIRST: under
            # pure latency-sensitive traffic the least-loaded branch
            # would otherwise always find a free live credit and the
            # rebuilt replica would stay half-open (N-1 capacity)
            # until a saturation burst -- closing it back to live is
            # what latency-sensitive traffic needs most.
            for index in range(count):
                if self.states[index] == REPLICA_HALF_OPEN \
                        and not self.canary_inflight[index] \
                        and self.active[index] == 0:
                    self._rr = index + 1
                    return index
            best = None
            for index in range(count):
                if self.states[index] == REPLICA_LIVE \
                        and self.active[index] < self.depth \
                        and (best is None
                             or self.active[index] < self.active[best]):
                    best = index
            if best is not None:
                self._rr = best + 1
                return best
        for offset in range(count):
            index = (self._rr + offset) % count
            state = self.states[index]
            if state == REPLICA_LIVE \
                    and self.active[index] < self.depth:
                self._rr = index + 1
                return index
            if state == REPLICA_HALF_OPEN \
                    and not self.canary_inflight[index] \
                    and self.active[index] == 0:
                self._rr = index + 1
                return index
        return None

    def admit(self, index: int) -> None:
        if self.states[index] == REPLICA_HALF_OPEN:
            self.canary_inflight[index] = True
        self.active[index] += 1
        self.admitted[index] += 1
        if self.active[index] == 1:
            self._busy_since[index] = time.monotonic()

    def release(self, index: int, ok: bool | None = True) -> None:
        """Return a replica credit.  A half-open slot's canary outcome
        decides its fate: success closes it live (full re-admission),
        failure re-kills it.  ``ok=None`` is NO verdict (the canary
        frame was yanked administratively -- replayed off a different
        stage's failure -- before this stage could prove anything): the
        slot stays half-open and the next admission is its canary."""
        if index >= len(self.states):
            return
        if self.active[index] > 0:
            self.active[index] -= 1
            if self.active[index] == 0 \
                    and self._busy_since[index] is not None:
                self._busy[index] += \
                    time.monotonic() - self._busy_since[index]
                self._busy_since[index] = None
        if self.states[index] == REPLICA_HALF_OPEN \
                and self.canary_inflight[index]:
            self.canary_inflight[index] = False
            if ok is not None:
                self._transition(index,
                                 REPLICA_LIVE if ok else REPLICA_DEAD)

    # -- lifecycle ---------------------------------------------------------

    def _transition(self, index: int, state: str) -> None:
        self.states[index] = state
        self.transitions.append((index, state, time.monotonic()))

    def fail(self, index: int) -> None:
        if index < len(self.states) \
                and self.states[index] != REPLICA_DEAD:
            self.failovers += 1
            self.canary_inflight[index] = False
            self._transition(index, REPLICA_DEAD)

    def rebuild(self, count: int, half_open=()) -> None:
        """Reset the group after a placement rebuild/re-split: every
        slot becomes live except the ``half_open`` indices, which
        re-admit behind a single canary frame each."""
        half_open = set(half_open)
        self.rebuilds += 1
        self.states = [REPLICA_HALF_OPEN if index in half_open
                       else REPLICA_LIVE
                       for index in range(max(1, int(count)))]
        self.active = [0] * len(self.states)
        self.admitted = [0] * len(self.states)
        self.canary_inflight = [False] * len(self.states)
        self._busy = [0.0] * len(self.states)
        self._busy_since = [None] * len(self.states)
        self._rr = 0
        for index in range(len(self.states)):
            self.transitions.append(
                (index, self.states[index], time.monotonic()))

    def set_depth(self, depth: int) -> None:
        """Live re-tune of the per-replica credit window (the fleet
        controller's queue-dominated actuator, ISSUE 20).  Shrinking
        never yanks an in-flight frame -- admission just stalls until
        the slot drains below the new window."""
        self.depth = max(1, int(depth))

    def reopen(self, index: int) -> bool:
        """Demote a LIVE slot back to half-open so its next admission
        is a single canary frame (the controller's canary-gated
        version swap, ISSUE 20: swap the element parameter, then prove
        the new version on one frame before full re-admission).  Dead
        and already-half-open slots are left alone; returns whether
        the transition happened."""
        if index >= len(self.states) \
                or self.states[index] != REPLICA_LIVE:
            return False
        self.canary_inflight[index] = False
        self._transition(index, REPLICA_HALF_OPEN)
        return True

    def live(self) -> int:
        return sum(1 for state in self.states if state == REPLICA_LIVE)

    def all_dead(self) -> bool:
        return all(state == REPLICA_DEAD for state in self.states)

    # -- occupancy ---------------------------------------------------------

    def reset_window(self) -> None:
        now = time.monotonic()
        for index in range(len(self.states)):
            self._busy[index] = 0.0
            if self._busy_since[index] is not None:
                self._busy_since[index] = now
        self._window_start = now

    def occupancy(self, index: int) -> float:
        wall = time.monotonic() - self._window_start
        if wall <= 0 or index >= len(self._busy):
            return 0.0
        busy = self._busy[index]
        if self._busy_since[index] is not None:
            busy += time.monotonic() - self._busy_since[index]
        return min(1.0, busy / wall)

    @property
    def stats(self) -> dict:
        return {"states": list(self.states),
                "active": list(self.active),
                "admitted": list(self.admitted),
                "live": self.live(),
                "depth": self.depth,
                "failovers": self.failovers,
                "rebuilds": self.rebuilds,
                "occupancy": [round(self.occupancy(index), 4)
                              for index in range(len(self.states))]}


class StageScheduler:
    """Credit-based per-stage admission + occupancy accounting.

    Owned by the event loop: every method except the workers' own job
    bodies runs on the pipeline's actor thread, so no locking.  The
    waiter tokens are opaque ``(stream_id, frame_id, node_name)``
    triples the engine re-posts as ``enter_stage_frame`` continuations.
    """

    def __init__(self, stages, depth: int = STAGE_INFLIGHT_DEFAULT,
                 replicas: dict | None = None, qos=None,
                 on_promote=None):
        self.depth = max(1, int(depth))
        self.stages = list(stages)
        # Unified QoS admission (ISSUE 12): when the pipeline carries a
        # QosScheduler, waiter pops rank by (class, ingest seq) instead
        # of FIFO -- an interactive frame overtakes queued batch frames
        # at the credit window, the second of the four former admission
        # planes.  ``on_promote(stream_id, frame)`` fires the first
        # time a frame's near-deadline promotion decides a pop (the
        # engine records/counts it).
        self._qos = qos
        self._on_promote = on_promote
        # Replicated stages (ISSUE 7): stage -> ReplicaGroup.  The
        # group owns per-replica credits; the per-stage counters below
        # keep tracking the TOTAL so occupancy/stats stay uniform.
        self.groups: dict[str, ReplicaGroup] = {
            stage: ReplicaGroup(stage, count, self.depth)
            for stage, count in (replicas or {}).items()}
        self._active: dict[str, int] = {s: 0 for s in self.stages}
        self._waiters: dict[str, deque] = {s: deque() for s in self.stages}
        # Credits promised to POPPED waiter tokens whose resume posts
        # are still in the mailbox: fresh admissions must not steal
        # them, or a later frame overtakes an earlier one through the
        # stage (the reorder buffer would still order the RESPONSES,
        # but a stateful stage element would see frames out of order).
        self._reserved: dict[str, int] = {s: 0 for s in self.stages}
        self._executors: dict[str, StageExecutor] = {}
        # Occupancy: integrate the time each stage has >= 1 admitted
        # frame, over a resettable window (bench resets at the start of
        # its timed pass).
        self._busy: dict[str, float] = {s: 0.0 for s in self.stages}
        self._busy_since: dict[str, float | None] = \
            {s: None for s in self.stages}
        self._window_start = time.monotonic()
        self.admitted: dict[str, int] = {s: 0 for s in self.stages}
        self.queued: dict[str, int] = {s: 0 for s in self.stages}

    # -- workers -----------------------------------------------------------

    def executor(self, stage: str,
                 replica: int | None = None) -> StageExecutor:
        """The stage's FIFO worker -- or, for a replicated stage, the
        worker of ONE replica (each replica serializes its own submesh
        while peers run concurrently: that concurrency IS the dp-N
        speedup)."""
        key = stage if replica is None else (stage, replica)
        worker = self._executors.get(key)
        if worker is None:
            name = stage if replica is None else f"{stage}#{replica}"
            worker = self._executors[key] = StageExecutor(name)
        return worker

    # -- admission window --------------------------------------------------

    def set_depth(self, depth: int) -> None:
        """Live re-tune of the stage credit window (fleet controller,
        ISSUE 20).  Growing frees credits immediately -- the caller
        must walk ``_pump_stage`` to wake queued waiters into them;
        shrinking stops admitting until in-flight frames drain below
        the new window (nothing is yanked)."""
        self.depth = max(1, int(depth))
        for group in self.groups.values():
            group.set_depth(self.depth)

    def try_admit(self, stage: str, reserved: bool = False) -> bool:
        """``reserved`` marks the admission attempt of a popped waiter
        token, which consumes its reservation; a fresh attempt may only
        take capacity BEYOND the outstanding reservations (the reserved
        credits belong to earlier queued frames), but genuinely free
        surplus stays usable."""
        if reserved:
            self.cancel_reservation(stage)
        elif self._active.get(stage, 0) \
                + self._reserved.get(stage, 0) >= self.depth:
            return False
        if self._active.get(stage, 0) >= self.depth:
            return False
        self._count_admit(stage)
        return True

    def _count_admit(self, stage: str) -> None:
        self._active[stage] = self._active.get(stage, 0) + 1
        self.admitted[stage] = self.admitted.get(stage, 0) + 1
        if self._active[stage] == 1:
            self._busy_since[stage] = time.monotonic()

    def admit_replica(self, stage: str, reserved: bool = False,
                      least_loaded: bool = False) -> int | None:
        """Replicated-stage admission: returns the replica index the
        frame admits into (round-robin over live slots with a free
        per-replica credit; ``least_loaded`` for latency-sensitive QoS
        classes), or None when the group is full.  The reservation
        discipline mirrors ``try_admit`` -- a fresh attempt may only
        take capacity beyond the credits promised to popped waiter
        tokens."""
        group = self.groups[stage]
        if reserved:
            self.cancel_reservation(stage)
        elif group.free_slots() <= self._reserved.get(stage, 0):
            return None
        index = group.pick(least_loaded=least_loaded)
        if index is None:
            return None
        group.admit(index)
        self._count_admit(stage)
        return index

    def cancel_reservation(self, stage: str) -> None:
        if self._reserved.get(stage, 0) > 0:
            self._reserved[stage] -= 1

    def enqueue(self, stage: str, token, front: bool = False) -> None:
        """FIFO wait queue for a full stage; ``front`` requeues a token
        whose freed credit was stolen by an interleaving admission, so
        queue order (and per-stream frame order) is preserved."""
        waiters = self._waiters.setdefault(stage, deque())
        if front:
            waiters.appendleft(token)
        else:
            self.queued[stage] = self.queued.get(stage, 0) + 1
            waiters.append(token)

    def release(self, stage: str, replica: int | None = None,
                ok: bool | None = True):
        """Return one credit (the given replica's, for a replicated
        stage -- ``ok`` carries the canary verdict for a half-open
        slot); returns the next waiter token to resume (or None)."""
        group = self.groups.get(stage)
        if group is not None and replica is not None:
            group.release(replica, ok=ok)
        if self._active.get(stage, 0) > 0:
            self._active[stage] -= 1
            if self._active[stage] == 0 \
                    and self._busy_since.get(stage) is not None:
                self._busy[stage] = self._busy.get(stage, 0.0) + \
                    time.monotonic() - self._busy_since[stage]
                self._busy_since[stage] = None
        return self.next_waiter(stage)

    def _has_capacity(self, stage: str) -> bool:
        group = self.groups.get(stage)
        if group is not None:
            return group.free_slots() > self._reserved.get(stage, 0)
        return self._active.get(stage, 0) \
            + self._reserved.get(stage, 0) < self.depth

    def next_waiter(self, stage: str):
        """Pop the next waiter when an unreserved credit is available
        (used both on release and when a popped waiter turned out
        dead); the popped token takes a reservation on that credit
        until its admission post lands.  Without a QosScheduler the
        pop is FIFO exactly as before; with one it picks the
        best-ranked waiter -- (effective class, ingest seq), so
        priority reorders across streams while same-class tokens keep
        arrival order and a front-requeued token (stolen credit) still
        wins its class on the seq tiebreak."""
        waiters = self._waiters.get(stage)
        if waiters and self._has_capacity(stage):
            self._reserved[stage] = self._reserved.get(stage, 0) + 1
            if self._qos is not None and len(waiters) > 1:
                return self._pop_ranked(waiters)
            return waiters.popleft()
        return None

    def _pop_ranked(self, waiters: deque):
        """Remove and return the best-ranked waiter token (tokens are
        ``[stream_id, frame_id, node_name, True, frame]`` lists; the
        Frame rides last).  Promotion decisions surface through
        ``on_promote`` exactly once per frame."""
        now = time.monotonic()
        best_index, best_rank = 0, None
        for index, token in enumerate(waiters):
            frame = token[-1]
            promoted_before = getattr(frame, "qos_promoted", False)
            rank = self._qos.rank_frame(frame, now)
            if not promoted_before \
                    and getattr(frame, "qos_promoted", False) \
                    and self._on_promote is not None:
                self._on_promote(token[0], frame)
            if best_rank is None or rank < best_rank:
                best_index, best_rank = index, rank
        token = waiters[best_index]
        del waiters[best_index]
        return token

    def waiting(self, stage: str) -> int:
        return len(self._waiters.get(stage, ()))

    def active(self, stage: str) -> int:
        return self._active.get(stage, 0)

    # -- occupancy ---------------------------------------------------------

    def reset_window(self) -> None:
        now = time.monotonic()
        for stage in self.stages:
            self._busy[stage] = 0.0
            if self._busy_since.get(stage) is not None:
                self._busy_since[stage] = now
        for group in self.groups.values():
            group.reset_window()
        self._window_start = now

    def occupancy(self, stage: str) -> float:
        wall = time.monotonic() - self._window_start
        if wall <= 0:
            return 0.0
        busy = self._busy.get(stage, 0.0)
        if self._busy_since.get(stage) is not None:
            busy += time.monotonic() - self._busy_since[stage]
        return min(1.0, busy / wall)

    # -- reporting ---------------------------------------------------------

    def _executed(self, stage: str) -> int:
        """Worker jobs completed for a stage, summed over its replica
        workers when replicated."""
        return sum(worker.executed
                   for key, worker in self._executors.items()
                   if key == stage
                   or (isinstance(key, tuple) and key[0] == stage))

    @property
    def stats(self) -> dict:
        result = {}
        for stage in self.stages:
            entry = {"active": self._active.get(stage, 0),
                     "admitted": self.admitted.get(stage, 0),
                     "queued": self.queued.get(stage, 0),
                     "waiting": self.waiting(stage),
                     "reserved": self._reserved.get(stage, 0),
                     "depth": self.depth,
                     # Worker jobs the stage's executor completed --
                     # with "admitted" this localizes a stall to
                     # admission (credits) vs execution (worker).
                     "executed": self._executed(stage),
                     "occupancy": round(self.occupancy(stage), 4)}
            group = self.groups.get(stage)
            if group is not None:
                entry["replicas"] = group.stats
            result[stage] = entry
        return result

    def stop(self):
        for worker in self._executors.values():
            worker.stop()
        self._executors.clear()
