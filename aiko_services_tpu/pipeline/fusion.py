"""Fused device-segment compilation: one XLA dispatch per pipeline
segment (ISSUE 2 tentpole).

PR 1 made swag device-resident between device elements, but the engine
still paid one jitted dispatch per element per frame -- N host round
trips and N sets of live intermediate HBM buffers for an N-element
device chain.  Profiled model segmentation across multi-TPU systems
(arXiv:2503.01025) and topology-aware auto-parallel inference (AoiZora,
arXiv:2606.17566) both identify exactly this dispatch/segmentation
overhead as the dominant non-compute cost.  With residency enforced,
contiguous device-pure elements are legal to trace into a single XLA
computation; this module does that:

- :func:`partition` walks a stream's execution path and groups maximal
  chains of *fusable* nodes into :class:`FusedSegment`\\ s.  A node is
  fusable when its element declares a pure :class:`DeviceFn` (the
  element-author contract, ``PipelineElement.device_fn``), is
  ``device_resident``, has no ``host_inputs`` / host-typed definition
  inputs (wire sinks), does not take the async park path this stream
  (the MicroBatcher boundary), is not a control-flow Loop element, and
  is not a placed stage head (the ICI stage hop is a boundary).
- :class:`FusedSegment` traces every member's ``device_fn`` into ONE
  function and jits it through a :class:`~.tensor.JitCache` keyed on
  input avals, so a whole segment executes as a single device call per
  frame.  Swag values that the segment consumes AND overwrites -- and
  that were produced by an earlier element of the same frame, with no
  other swag alias -- are **donated** (``donate_argnames``) so XLA
  reuses their HBM for the segment's outputs.  Donation is gated off on
  the CPU backend (``donate_argnums_supported``), where XLA miscompiles
  the aliasing.
- :func:`setup_compilation_cache` wires jax's persistent compilation
  cache (env-gated: ``AIKO_COMPILE_CACHE_DIR``, or the
  ``compile_cache_dir`` pipeline parameter) at Pipeline startup, so a
  process restart replays compiled segments from disk instead of
  re-tracing them.

The ``fuse`` pipeline/stream parameter gates the whole path:
``auto`` (default) fuses where legal, ``off`` always walks per-element.
Retry/resume paths (``retry_frame_at``, ``resume_frame_local``) always
execute per-element, so mid-segment recovery never replays half a
segment.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable

import jax

from .element import PipelineElement, PipelineElementLoop
from .tensor import JitCache
from ..observability import LogHistogram
from ..parallel.mesh import donate_argnums_supported
from ..utils import get_logger

__all__ = ["DeviceFn", "FusedSegment", "FusionError", "partition",
           "fusable", "setup_compilation_cache", "FUSE_MODES"]

_logger = get_logger("aiko.fusion")

FUSE_MODES = ("auto", "off")


class FusionError(RuntimeError):
    """Segment build/trace failure -- the engine falls back to unfused
    per-element execution and poisons the segment."""


@dataclasses.dataclass(frozen=True)
class DeviceFn:
    """Element-author contract for a fusable pure device computation.

    ``fn(**inputs, **captures) -> dict`` must be traceable under
    ``jax.jit`` with NO host side effects: no ``device_get``/``float()``
    syncs, no IO, no control-flow StreamEvents -- fused execution always
    maps the returned values out as OKAY.  ``inputs`` are the element
    definition input names the trace consumes (anything else the
    definition declares is routed around the trace); ``captures`` are
    extra device-resident values (weights) fed to the trace as real
    arguments -- never closed over, so they are not baked into the
    executable as constants and never donated.

    ``outputs`` are the returned keys written to the swag as
    device-resident element outputs.  Declared element outputs that are
    neither in ``outputs`` nor ``finalize_outputs`` must name an
    identically-named input: the engine passes the (possibly host-side)
    value through OUTSIDE the trace, preserving its type -- e.g.
    ``sample_rate`` riding through AudioFFT as a plain int.

    ``finalize(fetched) -> dict`` is an optional host step at segment
    map-out: the engine fetches ``finalize_inputs`` (returned trace
    values) with ONE counted ``TransferLedger.fetch`` and the callback
    builds the element's host-side outputs (``finalize_outputs``), e.g.
    the Detector's overlay/detections from its device slate.

    The purity half of this contract is statically enforced: the
    ``device-fn-host-call`` lint rule (analysis/residency.py) AST-scans
    every ``device_fn`` trace body at ``pipeline create``, so a host
    sync that would poison the fused segment on first trace is rejected
    before any frame is dispatched.
    """

    fn: Callable
    inputs: tuple = ()
    outputs: tuple = ()
    captures: dict = dataclasses.field(default_factory=dict)
    finalize: Callable | None = None
    finalize_inputs: tuple = ()
    finalize_outputs: tuple = ()


class _Step:
    """One element's slot inside a fused segment (planning product)."""

    __slots__ = ("node", "dfn", "in_keys", "pass_map")

    def __init__(self, node, dfn: DeviceFn):
        self.node = node
        self.dfn = dfn
        self.in_keys: dict[str, str] = {}     # fn input -> values key
        self.pass_map: dict[str, tuple] = {}  # out -> ("trace"|"ext", key)


def fusable(pipeline, node, stream) -> DeviceFn | None:
    """The partitioner's membership test; returns the element's
    DeviceFn when ``node`` may join a fused segment for ``stream``."""
    element = node.element
    if not isinstance(element, PipelineElement) \
            or not element.device_resident:
        return None
    if isinstance(element, PipelineElementLoop):
        return None                   # control flow re-enters the path
    if element.host_inputs:
        return None                   # wire sink: host materialization
    definition = element.definition
    if definition is None:
        return None
    declared_in = {io["name"]: io for io in definition.input}
    for io in definition.input:
        if str(io.get("type", "")).rstrip("?") == "host":
            return None               # host-typed input: sink boundary
    if element.frame_is_async(stream):
        return None                   # MicroBatcher / async park boundary
    placement = getattr(pipeline, "stage_placement", None)
    if placement is not None and node.name in placement.plans:
        return None                   # stage hop (ICI reshard) boundary
    try:
        dfn = element.device_fn(stream)
    except Exception:
        _logger.exception("%s: device_fn raised; not fusing", node.name)
        return None
    if dfn is None:
        return None
    if not set(dfn.inputs) <= set(declared_in):
        _logger.warning("%s: device_fn inputs %s not all declared; "
                        "not fusing", node.name, dfn.inputs)
        return None
    declared_out = [io["name"] for io in definition.output]
    for name in declared_out:
        if name in dfn.outputs or name in dfn.finalize_outputs:
            continue
        if name not in declared_in:   # passthrough needs a same-named in
            _logger.warning("%s: output %r neither computed nor "
                            "passthrough; not fusing", node.name, name)
            return None
    if set(dfn.captures) & set(dfn.inputs):
        _logger.warning("%s: capture names collide with inputs; "
                        "not fusing", node.name)
        return None
    return dfn


def qualified_reads(graph) -> frozenset:
    """Every producer-qualified (``El.name``-dotted) swag key any node's
    input mapping can read.  Donating a buffer whose qualified alias
    appears here would hand a later consumer a dead buffer, so such
    keys are never donated."""
    reads = set()
    for node in graph.nodes():
        for value in (node.properties or {}).values():
            if isinstance(value, str) and "." in value:
                reads.add(value)
    return frozenset(reads)


def partition(pipeline, nodes, stream) -> list:
    """Group maximal chains of fusable nodes (length >= 2) into
    FusedSegments; everything else stays a plain Node.  A node consuming
    a host value a finalize produced earlier in the chain starts a new
    chain -- device traces cannot read host-step products.

    Placed stage heads are partition boundaries (``fusable`` rejects
    them: the ICI hop + stage admission happen per-node), so segments
    are always STAGE-LOCAL; each segment records the placed stage it
    executes inside (``FusedSegment.stage_context`` -- the most recent
    placed head on the walk), which is what lets the engine run it on
    that stage's worker thread and attribute its dispatches to the
    stage.

    Segments are memoized per stream by their member-name tuple
    (``stream.fusion_segments``), so the full-path plan and the
    post-async resume suffix plans share one compiled segment instead
    of re-tracing the same chain per plan."""
    entries: list = []
    chain: list[tuple] = []
    chain_stage: list = [None]      # stage context when the chain began
    host_names: set[str] = set()
    cache = stream.fusion_segments
    placement = getattr(pipeline, "stage_placement", None)
    placed = set(placement.plans) if placement is not None else set()
    stage_context = None

    def flush():
        if len(chain) >= 2:
            key = tuple(node.name for node, _ in chain)
            segment = cache.get(key)
            if segment is None:
                segment = FusedSegment(pipeline,
                                       [n for n, _ in chain],
                                       [d for _, d in chain],
                                       stream_id=stream.stream_id,
                                       stage=chain_stage[0])
                cache[key] = segment
                pipeline.fused_segments.append(segment)
            entries.append(segment)
        else:
            entries.extend(n for n, _ in chain)
        chain.clear()
        host_names.clear()

    for node in nodes:
        if node.name in placed:
            stage_context = node.name
        dfn = fusable(pipeline, node, stream)
        if dfn is None:
            flush()
            entries.append(node)
            continue
        mapping = node.properties or {}
        consumed = {mapping.get(name, name) for name in dfn.inputs}
        if consumed & host_names:
            flush()
        if not chain:
            chain_stage[0] = stage_context
        chain.append((node, dfn))
        for out in dfn.finalize_outputs:
            host_names.add(out)
            host_names.add(f"{node.name}.{out}")
    flush()
    return entries


class FusedSegment:
    """A maximal chain of device-pure elements compiled and dispatched
    as ONE XLA computation per frame."""

    def __init__(self, pipeline, nodes, device_fns, stream_id=None,
                 stage=None):
        self.nodes = list(nodes)
        self.name = "+".join(node.name for node in nodes)
        # Segments resolve element parameters per stream (shapes,
        # width/height, synchronous) so they are stream-owned; the
        # pipeline registry prunes them when the stream dies.
        self.stream_id = stream_id
        # The placed stage whose submesh this segment's chain executes
        # on (None when the chain precedes any placed head): segments
        # are always stage-local, and a stage-tagged segment may run on
        # that stage's worker thread under stage-parallel execution.
        self.stage_context = stage
        self.steps: list[_Step] = []
        self.broken = False           # build/trace failed: run unfused
        self.calls = 0
        # donation is active off-CPU only; on CPU XLA miscompiles the
        # aliasing (see donate_argnums_supported) and d2h is zero-copy
        # anyway.
        self.donation = bool(donate_argnums_supported((0,)))
        self.jit_cache = JitCache(donate_argnames=("donate",)) \
            if self.donation else JitCache()
        # Qualified aliases any graph node's mapping may read: their
        # referents must never be donated (the consumer would see a
        # dead buffer after the stale-alias pop).
        self._qualified_reads = qualified_reads(pipeline.graph)
        self._reads: dict[str, dict] = {}     # swag key -> io spec
        self._traced_keys: set[str] = set()   # reads fed into the trace
        self._captures: dict[str, object] = {}
        self.overwritten: set[str] = set()    # bare swag keys we rewrite
        self._plan(device_fns)
        # One pinned binding: the JitCache keys on id(fn), and a fresh
        # bound-method object per access would never probe as a hit.
        self._traced_fn = self._traced
        self._call = self.jit_cache(self._traced_fn)
        # Per-dispatch wall time (telemetry plane): dispatch-cost
        # percentiles per segment.  LogHistogram itself is not
        # thread-safe (it normally sits behind MetricsRegistry's
        # lock); calls may come from the event loop OR a stage worker
        # while jit_stats() reads from the loop, so guard it here.
        self.dispatch_ms = LogHistogram()
        self._dispatch_lock = threading.Lock()

    # -- planning ----------------------------------------------------------

    def _plan(self, device_fns):
        # name -> ("trace", key) | ("ext", swag key) | ("host",) for
        # every value a later in-segment consumer could resolve.
        internal: dict[str, tuple] = {}
        for node, dfn in zip(self.nodes, device_fns):
            step = _Step(node, dfn)
            mapping = node.properties or {}
            declared_in = {io["name"]: io for io in
                           node.element.definition.input}
            for name in dfn.inputs:
                key = mapping.get(name, name)
                known = internal.get(key)
                if known is None:
                    step.in_keys[name] = key
                    self._reads.setdefault(key, declared_in[name])
                    self._traced_keys.add(key)
                elif known[0] == "trace":
                    step.in_keys[name] = known[1]
                elif known[0] == "ext":
                    step.in_keys[name] = known[1]
                    self._traced_keys.add(known[1])
                else:                 # host: partition() prevents this
                    raise FusionError(
                        f"{node.name}: input {name!r} is a host "
                        f"finalize product")
            for cap_name, value in dfn.captures.items():
                self._captures[f"{node.name}.__{cap_name}"] = value
            for name in dfn.outputs:
                trace_key = f"{node.name}.{name}"
                internal[name] = ("trace", trace_key)
                internal[trace_key] = ("trace", trace_key)
                self.overwritten.add(name)
            for name in dfn.finalize_outputs:
                internal[name] = ("host",)
                internal[f"{node.name}.{name}"] = ("host",)
                self.overwritten.add(name)
            for io in node.element.definition.output:
                name = io["name"]
                if name in dfn.outputs or name in dfn.finalize_outputs:
                    continue
                key = mapping.get(name, name)   # passthrough source
                known = internal.get(key)
                if known is not None and known[0] == "trace":
                    step.pass_map[name] = ("trace", known[1])
                    internal[name] = known
                else:
                    step.pass_map[name] = ("ext", key)
                    self._reads.setdefault(key, declared_in.get(
                        name, {"name": name, "type": "any?"}))
                    internal[name] = ("ext", key)
                self.overwritten.add(name)
            self.steps.append(step)

    # -- the fused computation ---------------------------------------------

    def _traced(self, keep, donate, captures):
        values = dict(keep)
        values.update(donate)
        values.update(captures)
        out = {}
        for step in self.steps:
            inputs = {name: values[key]
                      for name, key in step.in_keys.items()}
            inputs.update({name: values[f"{step.node.name}.__{name}"]
                           for name in step.dfn.captures})
            result = step.dfn.fn(**inputs)
            for name in step.dfn.outputs:
                value = result[name]
                trace_key = f"{step.node.name}.{name}"
                values[name] = value
                values[trace_key] = value
                out[trace_key] = value
            for name in step.dfn.finalize_inputs:
                out[f"{step.node.name}.{name}"] = result[name]
        return out

    # -- per-frame execution -----------------------------------------------

    def resolve(self, swag: dict) -> tuple[dict, list]:
        """(resolved external reads, missing non-optional keys)."""
        resolved, missing = {}, []
        for key, io in self._reads.items():
            if key in swag:
                resolved[key] = swag[key]
            elif str(io.get("type", "")).endswith("?") or "default" in io:
                resolved[key] = io.get("default")
            else:
                missing.append(key)
        return resolved, missing

    def donate_keys(self, resolved: dict, swag: dict,
                    produced: dict) -> set:
        """Traced inputs safe to donate: produced by an earlier element
        of THIS frame (never user/ingest data), overwritten by this
        segment (the swag key points at a fresh buffer afterwards), not
        aliased by any other swag entry, and whose producer-qualified
        alias no graph mapping can read after the segment."""
        if not self.donation:
            return set()
        keys = set()
        for key in self._traced_keys:
            if key not in resolved or key not in produced \
                    or key not in self.overwritten:
                continue
            value = resolved[key]
            if not isinstance(value, jax.Array):
                continue
            alias = f"{produced[key]}.{key}"
            if alias in self._qualified_reads:
                continue            # a downstream mapping reads it
            if any(entry is value for name, entry in swag.items()
                   if name not in (key, alias)):
                continue
            keys.add(key)
        return keys

    def _split(self, resolved: dict, donated: set) -> tuple[dict, dict]:
        keep = {key: resolved[key] for key in self._traced_keys
                if key not in donated}
        donate = {key: resolved[key] for key in donated}
        return keep, donate

    def would_compile(self, resolved: dict, donated: set,
                      replica: int | None = None) -> bool:
        keep, donate = self._split(resolved, donated)
        return self.jit_cache.probe(self._traced_fn,
                                    (keep, donate, self._captures),
                                    context=replica)

    def poison(self, reason: str) -> None:
        """Mark this segment broken: the cached plan splices its members
        back in on the next walk and every later frame runs per-element
        (trace/compile failure, injected segment fault)."""
        self.broken = True
        _logger.warning("segment %s poisoned: %s", self.name, reason)

    def call(self, resolved: dict, donated: set,
             replica: int | None = None) -> dict:
        """ONE device dispatch for the whole segment.  Returns the trace
        outputs dict keyed ``element.name``.

        ``replica`` keys the segment's JitCache per replica submesh of
        a replicated stage (ISSUE 7): jax re-specializes executables
        per sharding, so replica A's warm signature is still a cold
        compile on replica B -- the cache context keeps hit/miss and
        the compile probe honest per replica."""
        keep, donate = self._split(resolved, donated)
        self.calls += 1
        start = time.perf_counter()
        try:
            return self._call(keep, donate, self._captures,
                              _cache_context=replica)
        finally:
            elapsed_ms = (time.perf_counter() - start) * 1000.0
            with self._dispatch_lock:
                self.dispatch_ms.observe(elapsed_ms)

    @property
    def stats(self) -> dict:
        with self._dispatch_lock:
            dispatch_p50 = self.dispatch_ms.quantile(0.5,
                                                     windowed=False)
            dispatch_p99 = self.dispatch_ms.quantile(0.99,
                                                     windowed=False)
        return {"elements": [node.name for node in self.nodes],
                "calls": self.calls, "broken": self.broken,
                "donation": self.donation, "stage": self.stage_context,
                "dispatch_p50_ms": dispatch_p50,
                "dispatch_p99_ms": dispatch_p99,
                "jit": self.jit_cache.stats}

    def __repr__(self):
        return f"<FusedSegment {self.name}>"


# ---------------------------------------------------------------------------
# Persistent XLA compilation cache (env-gated, wired at Pipeline startup).

_CACHE_DIR_CONFIGURED: str | None = None


def setup_compilation_cache(parameters: dict | None = None) -> str | None:
    """Point jax's persistent compilation cache at a directory so
    process restarts replay compiled segments from disk instead of
    re-tracing + re-compiling them (cold-start kill).

    Gated: the ``AIKO_COMPILE_CACHE_DIR`` environment variable wins,
    else the ``compile_cache_dir`` pipeline parameter; absent both,
    nothing is configured.  Returns the directory in effect (idempotent
    across Pipelines -- the first configured directory stays; jax's
    cache config is process-global)."""
    global _CACHE_DIR_CONFIGURED
    path = os.environ.get("AIKO_COMPILE_CACHE_DIR") \
        or (parameters or {}).get("compile_cache_dir")
    if not path:
        return _CACHE_DIR_CONFIGURED
    path = str(path)
    if _CACHE_DIR_CONFIGURED is not None:
        if path != _CACHE_DIR_CONFIGURED:
            _logger.warning(
                "compile cache already at %s; ignoring %s "
                "(jax config is process-global)",
                _CACHE_DIR_CONFIGURED, path)
        return _CACHE_DIR_CONFIGURED
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for option, value in (
            # Cache every compile, however small/fast: pipeline segments
            # are exactly the many-small-programs workload the default
            # thresholds were tuned to exclude.
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(option, value)
        except AttributeError:        # pragma: no cover - jax drift
            _logger.debug("jax config %s unavailable", option)
    _CACHE_DIR_CONFIGURED = path
    _logger.info("persistent XLA compile cache -> %s", path)
    return path
