"""PipelineElement: the unit of dataflow computation (reference:
src/aiko_services/main/pipeline.py:376-673).

An element implements ``process_frame(stream, **inputs) -> (StreamEvent,
outputs_dict)`` plus optional ``start_stream``/``stop_stream`` lifecycle.
Elements do not subclass Actor -- they are plain objects owned by a
Pipeline (which IS an actor); this keeps per-element overhead at a method
call, not a mailbox hop.

Hierarchical parameter resolution (reference pipeline.py:557-595):
stream parameters (``Element.param`` qualified, then bare) -> element
definition parameters -> pipeline share/definition parameters.

Source elements create frames either one-shot (``create_frame``) or from a
generator pumped on a background thread with mailbox-depth backpressure
(``create_frames``, reference pipeline.py:471-551).

TPU extension: ``compile_element(stream)`` is called at start_stream time
so jitted computations warm their caches keyed on the stream's shapes.
"""

from __future__ import annotations

from typing import Any, Callable

from .stream import Stream, StreamEvent
from ..utils import get_logger, parse_bool

__all__ = ["PipelineElement", "PipelineElementLoop", "ElementContext"]

_NOT_FOUND = object()


class ElementContext:
    """Everything an element needs from its host pipeline."""

    __slots__ = ("name", "definition", "pipeline", "parameters")

    def __init__(self, name: str, definition, pipeline, parameters: dict):
        self.name = name
        self.definition = definition
        self.pipeline = pipeline
        self.parameters = parameters


class PipelineElement:
    #: Async-capable elements set this True and implement
    #: ``process_frame_start``; the engine then parks the frame at this
    #: stage and resumes it on completion, so multiple frames are in
    #: flight across stages (detect(k+1) overlaps decode(k)).
    is_async = False

    #: Device-resident swag contract (pipeline/overlap.py): elements
    #: hosting device computation set this True.  Their outputs may (and
    #: should) stay ``jax.Array`` -- un-synced, still computing -- and
    #: the engine runs their event-loop execution under the pipeline's
    #: transfer guard, so an implicit device->host sync inside one is
    #: recorded (policy ``log``) or fails the frame fast (policy
    #: ``disallow``) instead of silently halving throughput.
    device_resident = False

    #: Input names this element always needs materialized on host.  The
    #: engine fetches them all together (ONE counted ``jax.device_get``
    #: per element per frame) before ``process_frame`` -- the
    #: class-level complement of a definition input's
    #: ``"type": "host"``.  Everything else arrives as-is: device
    #: values stay device-resident between device stages.  The
    #: ``undeclared-host-input`` lint rule (analysis/residency.py)
    #: AST-checks ``process_frame`` bodies against this declaration at
    #: ``pipeline create``, so a quiet ``np.asarray(input)`` sync is a
    #: create-time finding instead of a frame-N transfer-guard error.
    host_inputs: tuple = ()

    def __init__(self, context: ElementContext):
        self.context = context
        self.name = context.name
        self.definition = context.definition
        self.pipeline = context.pipeline
        self.logger = get_logger(f"element.{self.name}")

    # -- core API (override) ----------------------------------------------

    def start_stream(self, stream: Stream, stream_id) \
            -> tuple[StreamEvent, dict]:
        return StreamEvent.OKAY, {}

    def process_frame(self, stream: Stream, **inputs) \
            -> tuple[StreamEvent, dict]:
        raise NotImplementedError

    def process_frame_start(self, stream: Stream, complete: Callable,
                            **inputs) -> None:
        """Non-blocking contract for ``is_async`` elements: submit the
        frame's work and return immediately; call
        ``complete(event, outputs)`` exactly once when it finishes (from
        any thread -- the call hops through the pipeline's mailbox).
        The engine parks the frame at this stage and resumes downstream
        elements on completion -- the local analogue of the remote
        park/forward/resume dance, so an accelerator-backed stage never
        serializes the event loop and frames overlap stages."""
        raise NotImplementedError

    def frame_is_async(self, stream: Stream) -> bool:
        """Whether this frame takes the parked/async path.  The
        ``synchronous`` parameter (stream/element/pipeline resolution)
        forces the blocking ``process_frame`` path on async-capable
        elements."""
        if not self.is_async:
            return False
        synchronous, found = self.get_parameter("synchronous", False)
        return not (found and parse_bool(synchronous))

    def stop_stream(self, stream: Stream, stream_id):
        return StreamEvent.OKAY, {}

    def compile_element(self, stream: Stream):
        """Optional: warm jit caches for this stream's shapes."""

    def device_fn(self, stream: Stream):
        """Fused-segment contract (pipeline/fusion.py): return a
        :class:`~.fusion.DeviceFn` describing this element's pure device
        computation, or None (default) when the element cannot fuse.

        Declaring one promises that, for this stream's parameters, the
        element's work is equivalent to ``fn(**inputs, **captures) ->
        outputs dict`` traced under ``jax.jit``: no host syncs, no IO,
        no StreamEvent control flow (fused execution always maps the
        results out as OKAY), and any host-side postprocessing expressed
        as the DeviceFn's ``finalize`` step.  The engine may then splice
        this element into a fused segment -- one XLA dispatch for the
        whole chain -- whenever it sits in a run of device-pure
        elements (no ``host_inputs``, no async/micro-batch park, no
        placement stage hop)."""
        return None

    # -- parameters --------------------------------------------------------

    def get_parameter(self, name: str, default=None,
                      use_pipeline: bool = True):
        """Returns (value, found).  Resolution order: per-replica
        override (the fleet controller's canary-gated version swap,
        ISSUE 20 -- only while a stage worker runs a specific replica)
        -> stream parameters (qualified ``Element.name`` first, then
        bare) -> element definition -> pipeline parameters."""
        replica = self.pipeline.current_replica() \
            if hasattr(self.pipeline, "current_replica") else None
        if replica is not None and replica[0] == self.name:
            value, found = self.pipeline.replica_override(
                self.name, replica[1], name)
            if found:
                return value, True
        stream = self.pipeline.current_stream()
        if stream is not None:
            qualified = f"{self.name}.{name}"
            if qualified in stream.parameters:
                return stream.parameters[qualified], True
            if name in stream.parameters:
                return stream.parameters[name], True
        if name in self.context.parameters:
            return self.context.parameters[name], True
        if use_pipeline:
            value = self.pipeline.get_pipeline_parameter(name, _NOT_FOUND)
            if value is not _NOT_FOUND:
                return value, True
        return default, False

    def set_parameter(self, name: str, value):
        self.context.parameters[name] = value

    # -- frame creation (source elements) ---------------------------------

    def create_frame(self, stream: Stream, frame_data: dict):
        self.pipeline.create_frame_local(stream, frame_data)

    def create_frames(self, stream: Stream,
                      frame_generator: Callable, rate: float | None = None):
        """Pump ``frame_generator(stream, frame_id) -> (StreamEvent,
        frame_data)`` on a background thread with backpressure."""
        self.pipeline.create_frame_generator(stream, self, frame_generator,
                                             rate)

    # -- misc --------------------------------------------------------------

    @property
    def input_names(self) -> list[str]:
        return self.definition.input_names if self.definition else []

    @property
    def output_names(self) -> list[str]:
        return self.definition.output_names if self.definition else []

    def my_id(self) -> str:
        return f"{self.pipeline.name}.{self.name}"

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class PipelineElementLoop(PipelineElement):
    """Control-flow marker: when its process_frame returns OKAY the
    pipeline jumps back to the ``loop_start`` element and re-runs the loop
    body; returning LOOP_END falls through to the successors (reference
    pipeline.py:1294-1304, elements/control/elements.py:20-57)."""
