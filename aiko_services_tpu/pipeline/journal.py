"""Durable per-pipeline stream journal (ISSUE 13 tentpole, layer 1).

Every fault the engine survives today is scoped to a single living
process: chip death replays in-flight frames (PR 5), replica loss
sheds to peers (PR 7), wire faults breaker-and-fallback (PR 9) -- but
SIGKILL the process and every live stream, parked frame and
mid-generation LLM request dies with it.  This module is the
process-boundary half of that story: a lightweight append-only journal
records each stream's *recoverable* state at its natural commit
points, so a surviving peer can reconstruct any live stream at its
last host-visible boundary.

What is journaled (and when):

- ``open``   stream creation: parameters (tenant/class/deadline),
  graph path and the response topic -- enough to recreate the stream
  with identical admission semantics on a peer.
- ``frame``  frame ingest: the frame id plus its HOST-VISIBLE input
  swag, wire-encoded by the frame codec.  Device-resident leaves are
  never fetched here (that would be a hidden sync on the hot path);
  they are skipped and the record is marked ``partial`` -- state past
  the journal horizon, honestly lost on failover.
- ``done``   response delivery: the commit point that PRUNES the
  frame from the live set.  A frame with no ``done`` record is
  *undelivered* and will be replayed by an adopter.
- ``llm``    per emitted token of an LLM stream: the committed prefix
  the ``_rebase`` machinery maintains, so an adopter resumes
  generation at the last emitted token instead of re-running (and
  re-streaming) the whole request.
- ``close``  graceful stream destroy: the whole stream leaves the
  live set (an adopter ignores it).
- ``drained``  clean cooperative shutdown marker (``drain`` command):
  everything undelivered above it is intentionally parked for
  adoption, nothing was lost mid-write.

Durability discipline: every record is WRITTEN (buffered + flushed to
the OS) immediately -- a crashed process's journal is complete up to
its last append on the same host -- while ``fsync`` is batched on a
time interval so the hot path never pays a disk sync per frame.  The
file is bounded: once the append count outgrows the live set a
compaction rewrites the journal from the in-memory mirror (tmp file +
atomic rename).

Adoption claims: :func:`claim_adoption` creates ``<path>.adopted``
with ``O_EXCL`` -- exactly one peer may adopt a dead pipeline's
journal; the second claimant is refused (double-adoption of a stream
would double-replay its undelivered frames).

jax-free by design, like faults/ and observability/: journaling and
recovery must work on a host whose accelerator just died.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .codec import encode_value, decode_value
from ..utils import get_logger

__all__ = ["StreamJournal", "JournalState", "StreamEntry",
           "load_journal", "claim_adoption", "adopter_of",
           "JOURNAL_FSYNC_MS_DEFAULT", "JOURNAL_COMPACT_RECORDS",
           "ADOPT_LIMIT_DEFAULT", "DRAIN_TIMEOUT_MS_DEFAULT"]

_logger = get_logger("aiko.journal")

JOURNAL_FSYNC_MS_DEFAULT = 50.0
#: appended records beyond the live set before a compaction rewrite.
JOURNAL_COMPACT_RECORDS = 4096
#: streams one ``adopt`` command will reconstruct (the ``adopt_limit``
#: parameter) -- bounded like ``replay_limit`` bounds chip-death
#: replays, so a pathological journal cannot wedge the adopter.
ADOPT_LIMIT_DEFAULT = 64
#: how long ``drain`` waits for in-flight frames before parking the
#: leftovers in the journal for adoption (``drain_timeout_ms``).
DRAIN_TIMEOUT_MS_DEFAULT = 5000.0


def _encodable(value) -> bool:
    """Host-visible leaf test WITHOUT importing jax: device arrays
    identify by their type's module.  Anything jax-typed is skipped
    (journal horizon), never fetched."""
    module = type(value).__module__ or ""
    return not (module.startswith("jax") or module.startswith("jaxlib"))


class StreamEntry:
    """One stream's state -- shared by the journal's in-memory mirror
    (compaction source) and the reader's reconstruction.

    The delivered set is kept BOUNDED by a contiguous-frontier
    watermark: delivery is in ingest order (the engine's reorder
    buffer), so delivered frames collapse into ``done_upto`` as the
    frontier advances, and only out-of-order stragglers (rare: a
    dropped frame's skipped slot) stay as explicit entries."""

    __slots__ = ("stream_id", "parameters", "graph_path",
                 "topic_response", "frames", "llm", "closed",
                 "done_upto")

    def __init__(self, stream_id: str):
        self.stream_id = stream_id
        self.parameters: dict = {}
        self.graph_path = None
        self.topic_response = None
        # frame_id -> {"data": encoded swag, "partial": bool,
        #              "delivered": bool, "ok": bool | None}; frames
        # at or below ``done_upto`` are popped (delivered, pruned).
        self.frames: dict = {}
        self.llm: dict = {}             # frame_id -> [committed tokens]
        self.closed = False
        self.done_upto = -1             # all ids <= this are delivered

    def mark_done(self, frame_id: int, ok) -> None:
        frame = self.frames.setdefault(
            int(frame_id), {"data": {}, "partial": False,
                            "delivered": False, "ok": None})
        frame["delivered"] = True
        frame["ok"] = None if ok is None else bool(ok)
        frame["data"] = {}
        self.llm.pop(int(frame_id), None)
        while True:
            frontier = self.frames.get(self.done_upto + 1)
            if frontier is None or not frontier.get("delivered"):
                break
            self.frames.pop(self.done_upto + 1)
            self.done_upto += 1

    def set_upto(self, frame_id: int) -> None:
        frame_id = int(frame_id)
        if frame_id <= self.done_upto:
            return
        for fid in [fid for fid in self.frames if fid <= frame_id]:
            self.frames.pop(fid)
        for fid in [fid for fid in self.llm if fid <= frame_id]:
            self.llm.pop(fid)
        self.done_upto = frame_id

    @property
    def undelivered(self) -> list:
        """Frame ids ingested but never delivered, in ingest order."""
        return sorted(fid for fid, entry in self.frames.items()
                      if not entry.get("delivered"))

    @property
    def delivered(self) -> list:
        explicit = {fid for fid, entry in self.frames.items()
                    if entry.get("delivered")}
        return sorted(set(range(self.done_upto + 1)) | explicit)


class JournalState:
    """Result of :func:`load_journal`."""

    __slots__ = ("streams", "drained", "records", "truncated")

    def __init__(self):
        self.streams: dict[str, StreamEntry] = {}
        self.drained = False
        self.records = 0
        self.truncated = False

    def live_streams(self) -> list:
        """Open (never gracefully closed) streams, creation-ordered."""
        return [entry for entry in self.streams.values()
                if not entry.closed]


class StreamJournal:
    """Append-only, fsync-batched journal for one pipeline.

    Thread-safe: the event loop appends ingest/delivery records while
    LLM device workers append token commits."""

    def __init__(self, path: str,
                 fsync_ms: float = JOURNAL_FSYNC_MS_DEFAULT,
                 compact_records: int = JOURNAL_COMPACT_RECORDS):
        self.path = str(path)
        self.fsync_ms = max(0.0, float(fsync_ms))
        self.compact_records = max(64, int(compact_records))
        self._lock = threading.Lock()
        self._live: dict[str, StreamEntry] = {}
        self._appended = 0              # records since last compaction
        self._pending_sync = 0          # records written, not fsynced
        self._last_sync = time.monotonic()
        self._sync_timer: threading.Timer | None = None
        self.appends = 0                # lifetime record count
        self.compactions = 0
        self.synced = 0                 # fsync calls
        self.partial_frames = 0         # device leaves past the horizon
        # Fresh incarnation: a restarting pipeline starts an empty
        # journal and clears any stale adoption claim, or its NEXT
        # death could never be adopted (the claim file fences by
        # path).  A previous incarnation that was adopted or cleanly
        # drained is discarded; one that was NEITHER (unclean death,
        # supervisor respawned faster than the LWT + adoption ran) is
        # preserved as ``<path>.orphaned`` -- an adopter that loses
        # the race reads the fresh (empty) file instead of state
        # vanishing mid-read, and the orphan stays recoverable by
        # hand: ``(adopt <path>.orphaned)``.
        try:
            if os.path.getsize(self.path) > 0 \
                    and not os.path.exists(f"{self.path}.adopted") \
                    and not load_journal(self.path).drained:
                os.replace(self.path, f"{self.path}.orphaned")
                _logger.warning(
                    "journal %s from the previous incarnation was "
                    "never adopted; preserved as %s.orphaned",
                    self.path, self.path)
        except OSError:
            pass
        try:
            os.unlink(f"{self.path}.adopted")
        except OSError:
            pass
        self._file = open(self.path, "w", encoding="utf-8")

    # -- record emission ---------------------------------------------------

    def _append(self, record: dict) -> int:
        """Write one record (flushed, fsync batched); returns the
        unsynced backlog AFTER the append -- the ``journal_lag``
        signal."""
        line = json.dumps(record, separators=(",", ":"))
        now = time.monotonic()
        with self._lock:
            self._file.write(line + "\n")
            self._file.flush()
            self.appends += 1
            self._appended += 1
            self._pending_sync += 1
            lag = self._pending_sync
            due = self.fsync_ms == 0.0 or \
                (now - self._last_sync) * 1000.0 >= self.fsync_ms
            if due:
                self._sync_locked()
                lag = 0
            else:
                # The batch must fsync even if NO further append ever
                # comes (a low-rate stream's last frame would
                # otherwise sit un-fsynced indefinitely -- far past
                # the journal_fsync_ms horizon the docs promise).
                self._arm_sync_timer_locked()
        return lag

    def _arm_sync_timer_locked(self) -> None:
        if self._sync_timer is not None:
            return
        timer = threading.Timer(self.fsync_ms / 1000.0,
                                self._timer_sync)
        timer.daemon = True
        self._sync_timer = timer
        timer.start()

    def _timer_sync(self) -> None:
        with self._lock:
            self._sync_timer = None
            if self._pending_sync:
                try:
                    self._file.flush()
                    self._sync_locked()
                except (OSError, ValueError):
                    pass                # closed mid-flight: no-op

    def _sync_locked(self) -> None:
        try:
            os.fsync(self._file.fileno())
        except OSError:
            pass
        self._pending_sync = 0
        self._last_sync = time.monotonic()
        self.synced += 1

    def sync(self) -> None:
        """Force the batched fsync (drain/shutdown commit point)."""
        with self._lock:
            self._file.flush()
            self._sync_locked()

    @property
    def lag(self) -> int:
        """Records written but not yet fsynced."""
        with self._lock:
            return self._pending_sync

    # -- commit points -----------------------------------------------------

    def stream_open(self, stream_id: str, parameters: dict,
                    graph_path=None, topic_response=None) -> int:
        stream_id = str(stream_id)
        entry = StreamEntry(stream_id)
        entry.parameters = self._safe_parameters(parameters)
        entry.graph_path = graph_path
        entry.topic_response = topic_response
        with self._lock:
            self._live[stream_id] = entry
        return self._append({"t": "open", "s": stream_id,
                             "params": entry.parameters,
                             "path": graph_path,
                             "topic": topic_response})

    def frame_ingested(self, stream_id: str, frame_id: int,
                       swag: dict, trace_id=None) -> int:
        stream_id = str(stream_id)
        data, partial = self._encode_swag(swag)
        if partial:
            self.partial_frames += 1
        with self._lock:
            entry = self._live.get(stream_id)
            if entry is not None:
                mirror = {"data": data, "partial": partial,
                          "delivered": False, "ok": None}
                if trace_id:
                    mirror["tid"] = str(trace_id)
                entry.frames[int(frame_id)] = mirror
        record = {"t": "frame", "s": stream_id, "f": int(frame_id),
                  "data": data}
        if partial:
            record["partial"] = True
        if trace_id:
            # A replay after adoption re-ingests with this trace_id:
            # the frame's spans keep joining its ORIGINAL door-to-
            # decode trace across the process kill.
            record["tid"] = str(trace_id)
        return self._append(record)

    def frame_done(self, stream_id: str, frame_id: int,
                   ok: bool = True) -> int:
        stream_id = str(stream_id)
        with self._lock:
            entry = self._live.get(stream_id)
            if entry is not None:
                # Delivered: the payload prunes and the frame folds
                # into the contiguous done_upto watermark.
                entry.mark_done(frame_id, ok)
        lag = self._append({"t": "done", "s": stream_id,
                            "f": int(frame_id), "ok": bool(ok)})
        self._maybe_compact()
        return lag

    def llm_token(self, stream_id: str, frame_id: int,
                  token: int) -> int:
        stream_id = str(stream_id)
        with self._lock:
            entry = self._live.get(stream_id)
            if entry is not None:
                entry.llm.setdefault(int(frame_id), []).append(int(token))
        return self._append({"t": "llm", "s": stream_id,
                             "f": int(frame_id), "tok": int(token)})

    def llm_tokens(self, stream_id: str, frame_id: int,
                   tokens: list) -> int:
        """Bulk commit (adoption re-journals an inherited prefix; the
        batcher's export path commits a whole request at once)."""
        stream_id = str(stream_id)
        tokens = [int(token) for token in tokens]
        if not tokens:
            return self.lag
        with self._lock:
            entry = self._live.get(stream_id)
            if entry is not None:
                entry.llm.setdefault(int(frame_id), []).extend(tokens)
        return self._append({"t": "llm", "s": stream_id,
                             "f": int(frame_id), "toks": tokens})

    def stream_close(self, stream_id: str) -> int:
        stream_id = str(stream_id)
        with self._lock:
            self._live.pop(stream_id, None)
        lag = self._append({"t": "close", "s": stream_id})
        self._maybe_compact()
        return lag

    def mark_drained(self) -> None:
        """Clean cooperative shutdown: everything undelivered is
        intentionally parked for adoption."""
        self._append({"t": "drained"})
        self.sync()

    # -- bounding ----------------------------------------------------------

    def _live_records(self) -> int:
        count = 0
        for entry in self._live.values():
            count += 1 + len(entry.frames) + len(entry.llm)
        return count

    def _maybe_compact(self) -> None:
        with self._lock:
            live = self._live_records()
            if self._appended < self.compact_records \
                    or self._appended < 2 * max(1, live):
                return
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the file from the live mirror (tmp + atomic
        rename): the delivered history collapses into one ``upto``
        watermark per stream, closed streams vanish."""
        tmp = f"{self.path}.compact"
        written = 0
        try:
            with open(tmp, "w", encoding="utf-8") as out:
                for entry in self._live.values():
                    records = [{"t": "open", "s": entry.stream_id,
                                "params": entry.parameters,
                                "path": entry.graph_path,
                                "topic": entry.topic_response}]
                    if entry.done_upto >= 0:
                        records.append({"t": "upto",
                                        "s": entry.stream_id,
                                        "f": entry.done_upto})
                    for fid in sorted(entry.frames):
                        frame = entry.frames[fid]
                        if frame.get("delivered"):
                            # an out-of-order straggler past the
                            # watermark
                            records.append({"t": "done",
                                            "s": entry.stream_id,
                                            "f": fid,
                                            "ok": frame.get("ok", True)})
                            continue
                        record = {"t": "frame", "s": entry.stream_id,
                                  "f": fid, "data": frame["data"]}
                        if frame.get("partial"):
                            record["partial"] = True
                        if frame.get("tid"):
                            record["tid"] = frame["tid"]
                        records.append(record)
                    for fid in sorted(entry.llm):
                        records.append({"t": "llm",
                                        "s": entry.stream_id, "f": fid,
                                        "toks": entry.llm[fid]})
                    for record in records:
                        out.write(json.dumps(
                            record, separators=(",", ":")) + "\n")
                        written += 1
                out.flush()
                os.fsync(out.fileno())
            self._file.close()
            os.replace(tmp, self.path)
            self._file = open(self.path, "a", encoding="utf-8")
            self._appended = written
            self._pending_sync = 0
            self._last_sync = time.monotonic()
            self.compactions += 1
        except OSError:
            _logger.exception("journal compaction failed; journal "
                              "keeps growing until the next attempt")
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _safe_parameters(parameters: dict) -> dict:
        """Stream parameters are JSON-encodable by construction
        (wire/gateway provenance); anything else degrades to str."""
        safe = {}
        for key, value in (parameters or {}).items():
            try:
                json.dumps(value)
                safe[str(key)] = value
            except (TypeError, ValueError):
                safe[str(key)] = str(value)
        return safe

    @staticmethod
    def _encode_swag(swag: dict) -> tuple[dict, bool]:
        """Host-visible swag -> wire-encoded payload.  Device leaves
        (jax-typed) are past the journal horizon: skipped, flagged."""
        data: dict = {}
        partial = False
        for key, value in (swag or {}).items():
            if "." in str(key):
                continue            # producer-qualified aliases rebuild
            if not _encodable(value):
                partial = True
                continue
            try:
                data[str(key)] = encode_value(value)
            except Exception:
                partial = True
        return data, partial

    def stats(self) -> dict:
        with self._lock:
            return {"path": self.path, "appends": self.appends,
                    "pending_sync": self._pending_sync,
                    "live_streams": len(self._live),
                    "live_records": self._live_records(),
                    "compactions": self.compactions,
                    "synced": self.synced,
                    "partial_frames": self.partial_frames}

    def close(self) -> None:
        with self._lock:
            if self._sync_timer is not None:
                self._sync_timer.cancel()
                self._sync_timer = None
            try:
                self._file.flush()
                self._sync_locked()
                self._file.close()
            except (OSError, ValueError):
                pass


# ---------------------------------------------------------------------------
# Recovery side: read a (possibly unclean) journal.

def load_journal(path: str) -> JournalState:
    """Reconstruct the live-stream state from a journal file.  A
    truncated final line (the process died mid-write) is tolerated:
    everything before it is intact -- records are flushed whole and
    newline-terminated."""
    state = JournalState()
    try:
        with open(path, "r", encoding="utf-8") as stream:
            for line in stream:
                if not line.endswith("\n"):
                    state.truncated = True
                    break           # torn tail: stop, keep the prefix
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    state.truncated = True
                    break
                state.records += 1
                _apply(state, record)
    except OSError as error:
        _logger.warning("journal %s unreadable: %s", path, error)
    return state


def _apply(state: JournalState, record: dict) -> None:
    kind = record.get("t")
    if kind == "drained":
        state.drained = True
        return
    stream_id = str(record.get("s", ""))
    if not stream_id:
        return
    if kind == "open":
        entry = StreamEntry(stream_id)
        entry.parameters = dict(record.get("params") or {})
        entry.graph_path = record.get("path")
        entry.topic_response = record.get("topic")
        state.streams[stream_id] = entry
        return
    entry = state.streams.get(stream_id)
    if entry is None:
        entry = StreamEntry(stream_id)
        state.streams[stream_id] = entry
    if kind == "frame":
        mirror = {"data": dict(record.get("data") or {}),
                  "partial": bool(record.get("partial", False)),
                  "delivered": False, "ok": None}
        if record.get("tid"):
            mirror["tid"] = str(record["tid"])
        entry.frames[int(record.get("f", 0))] = mirror
    elif kind == "done":
        entry.mark_done(int(record.get("f", 0)),
                        record.get("ok", True))
    elif kind == "upto":
        entry.set_upto(int(record.get("f", -1)))
    elif kind == "llm":
        tokens = entry.llm.setdefault(int(record.get("f", 0)), [])
        if "toks" in record:
            tokens.extend(int(token) for token in record["toks"])
        else:
            tokens.append(int(record.get("tok", 0)))
    elif kind == "close":
        entry.closed = True


def decode_payload(data: dict) -> dict:
    """Journaled frame payload -> ingestable swag (codec twin of the
    encode in ``frame_ingested``)."""
    return {key: decode_value(value) for key, value in
            (data or {}).items()}


# ---------------------------------------------------------------------------
# Adoption claims.

def claim_adoption(path: str, adopter: str) -> bool:
    """Claim a dead pipeline's journal for ``adopter``.  Exactly one
    claimant wins (``O_EXCL`` create of ``<path>.adopted``); everyone
    else is refused -- a stream adopted twice would double-replay its
    undelivered frames to the client."""
    try:
        fd = os.open(f"{path}.adopted",
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return False
    except OSError as error:
        _logger.warning("adoption claim on %s failed: %s", path, error)
        return False
    with os.fdopen(fd, "w") as stream:
        stream.write(json.dumps({"adopter": str(adopter),
                                 "time": time.time()}))
    return True


def adopter_of(path: str) -> str | None:
    """Who claimed this journal, or None."""
    try:
        with open(f"{path}.adopted", "r", encoding="utf-8") as stream:
            return str(json.load(stream).get("adopter"))
    except (OSError, ValueError):
        return None
