"""Overlapped frame execution: device-resident swag accounting and the
bounded per-stream dispatch window (ISSUE 1 tentpole; Vortex
arXiv:2511.02062 and the profiled-segmentation multi-TPU work
arXiv:2503.01025 both identify host/device overlap + device residency as
what turns component-fast pipelines into end-to-end-fast ones).

Two small engine-side mechanisms:

- :class:`TransferLedger` enforces and accounts the **device-resident
  swag contract**: between consecutive device elements swag values stay
  ``jax.Array`` in HBM; the host only sees them at a sink (wire
  response, process boundary) or at an input explicitly declared
  host-typed.  Device elements run under
  ``jax.transfer_guard_device_to_host`` with the configured policy
  (pipeline parameter ``transfer_guard``: ``allow`` | ``log`` |
  ``disallow``), every engine-initiated fetch is ONE counted
  ``jax.device_get`` of the whole tree, and a software residency check
  catches declared-``tensor`` outputs that come back as host arrays --
  the CPU backend's device-to-host "transfers" are zero-copy so the
  jax guard never fires there, but the residency check does, which is
  what lets tier-1 tests fail fast on host-sync regressions without
  TPU hardware.

- :class:`DeviceWindow` bounds how far dispatch runs ahead of compute:
  jitted elements return un-synced arrays and frames complete without a
  host sync, so a fast source could otherwise enqueue unbounded device
  work (and pin unbounded HBM in not-yet-computed results).  Each
  completed frame's device leaves are noted; ingesting a new frame
  paces the window by ``block_until_ready``-ing the OLDEST noted frame
  until at most ``device_inflight`` frames (default triple buffering)
  are outstanding -- classic double/triple buffering per stream.

The stage-keyed sibling of the DeviceWindow lives in
:mod:`~aiko_services_tpu.pipeline.stages`: multi-stage PLACED pipelines
additionally pace admission per placed stage (``stage_inflight``,
credit-based backpressure) so frames overlap ACROSS submeshes, while
this module's window keeps any one stream's dispatch bounded ahead of
compute.  The two compose: ingest pacing bounds total outstanding
device work, stage credits bound where in the pipeline it sits.

Unified QoS (ISSUE 12): the window depth ``pace()`` is called with is
no longer always the stream's raw ``device_inflight`` -- when the
pipeline carries a :class:`~aiko_services_tpu.gateway.qos.QosScheduler`
the limit is the stream's CLASS-capped depth
(``Pipeline._device_limit`` -> ``QosScheduler.device_limit``), so a
``batch``-class stream can be held to double buffering while
``interactive`` keeps the full window on the same pipeline.  The
window itself stays policy-free: it paces to whatever limit the one
scheduler resolves, which is exactly what makes this seam plane 1 of
the unified admission refactor.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

import jax
import numpy as np

__all__ = ["TransferLedger", "DeviceWindow", "device_leaves",
           "touches_devices", "DEVICE_INFLIGHT_DEFAULT"]

TRANSFER_POLICIES = ("allow", "log", "disallow")

# Default bounded async-dispatch window per stream (triple buffering);
# override with the ``device_inflight`` pipeline/stream parameter
# (0 disables pacing).
DEVICE_INFLIGHT_DEFAULT = 3


def device_leaves(tree) -> list:
    """Every ``jax.Array`` leaf of a swag/pytree (host values skipped)."""
    return [leaf for leaf in jax.tree_util.tree_leaves(tree)
            if isinstance(leaf, jax.Array)]


def touches_devices(tree, devices: set) -> bool:
    """True when any device leaf of ``tree`` lives (even partly) on one
    of ``devices`` -- the replay path's test for swag values stranded on
    dead chips.  A leaf whose device set cannot be read (deleted buffer,
    backend drift) counts as touching: recovery must treat it as
    compromised, not silently keep it."""
    for leaf in device_leaves(tree):
        try:
            if set(leaf.devices()) & devices:
                return True
        except Exception:
            return True
    return False


class TransferLedger:
    """Counts (and can forbid) host transfers on the frame path.

    ``implicit`` counts contract violations: transfers the engine did
    not initiate -- a jax transfer-guard error raised inside a device
    element (policy ``disallow`` on real hardware), or a
    declared-``tensor`` output arriving as a host ``np.ndarray`` (any
    policy except ``allow``, any backend).  Under ``log`` the jax-level
    guard only writes to jax's own log (nothing raises, so nothing can
    be counted from it); the residency check is what increments the
    counter there.  ``explicit`` counts engine-initiated fetches
    (host-typed inputs, process-boundary encodes), each ONE
    ``jax.device_get`` of the whole tree regardless of leaf count.
    Healthy pipelines keep ``implicit`` at 0; the bench reports it as
    ``swag_host_transfers``.
    """

    def __init__(self, policy: str = "allow"):
        policy = str(policy or "allow").strip().lower()
        if policy not in TRANSFER_POLICIES:
            raise ValueError(f"transfer_guard={policy!r}: one of "
                             f"{TRANSFER_POLICIES}")
        self.policy = policy
        self.implicit = 0
        self.explicit = 0
        # Labeled sub-counts of ``explicit`` (e.g. the LLM element's
        # per-decode-block fetch, label "llm_block"): lets tests and
        # the bench assert a path pays EXACTLY one fetch per unit of
        # work, not merely "some" fetches.
        self.explicit_by_label: dict = {}
        # Counters are bumped from the event loop AND stage-worker
        # threads (pipeline/stages.py): unsynchronized += would lose
        # increments.
        self._count_lock = threading.Lock()

    @property
    def active(self) -> bool:
        return self.policy != "allow"

    @contextlib.contextmanager
    def guard(self):
        """Wrap one device element's event-loop execution.  Thread-local
        (jax config context), so an element's own fetch worker threads
        are unaffected -- fetching at the element's sink is its job."""
        if not self.active:
            yield
            return
        with jax.transfer_guard_device_to_host(self.policy):
            yield

    def record_implicit(self, count: int = 1):
        with self._count_lock:
            self.implicit += count

    @staticmethod
    def is_guard_error(error: BaseException) -> bool:
        message = str(error).lower()
        return "transfer" in message and "disallow" in message

    def fetch(self, tree, label: str | None = None):
        """ONE explicit host fetch of every device leaf in ``tree``
        (non-array leaves pass through untouched -- strings/lists/dicts
        in a swag must not become numpy).  Counted once per call, not
        per leaf -- under ``label`` too when given (the device-loop
        serving contract: one "llm_block" fetch per retired block);
        runs under an ``allow`` scope so the engine's own sinks never
        trip the guard they enforce."""
        leaves = device_leaves(tree)
        if not leaves:
            return tree
        with self._count_lock:
            self.explicit += 1
            if label:
                self.explicit_by_label[label] = \
                    self.explicit_by_label.get(label, 0) + 1
        with jax.transfer_guard_device_to_host("allow"):
            for leaf in leaves:
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()     # gather copies in flight
            fetched = iter(jax.device_get(leaves))
        return jax.tree_util.tree_map(
            lambda leaf: next(fetched)
            if isinstance(leaf, jax.Array) else leaf, tree)

    def residency_violations(self, element, outputs: dict) -> list[str]:
        """Declared device outputs (definition ``"type": "tensor"`` /
        ``"device"``) that came back host-resident: the software twin of
        the jax guard, effective on every backend."""
        declared = element.definition.output if element.definition else []
        violations = []
        for io in declared:
            io_type = str(io.get("type", "")).rstrip("?")
            if io_type not in ("tensor", "device"):
                continue
            value = outputs.get(io["name"])
            if value is not None and isinstance(value, np.ndarray):
                violations.append(io["name"])
        return violations

    @property
    def stats(self) -> dict:
        return {"policy": self.policy, "implicit": self.implicit,
                "explicit": self.explicit,
                "explicit_by_label": dict(self.explicit_by_label)}


class DeviceWindow:
    """Per-stream bounded in-flight accounting of dispatched-but-unsynced
    frames (double/triple buffering).  Owned by the event loop; no
    locking."""

    def __init__(self):
        self._inflight: deque = deque()      # (frame_id, device leaves)
        self.noted = 0                       # frames entering the window
        self.synced = 0                      # frames paced to completion
        self.invalidated = 0                 # entries dropped on dead chips

    def note(self, frame_id: int, swag) -> None:
        """Register a completed frame's outstanding device work."""
        leaves = device_leaves(swag)
        if leaves:
            self._inflight.append((frame_id, leaves))
            self.noted += 1

    def pace(self, limit) -> float:
        """Block (oldest-first) until at most ``limit - 1`` frames stay
        outstanding, so the frame about to dispatch makes ``limit``.
        ``limit`` <= 0 or None disables pacing (unbounded dispatch).
        Returns the seconds spent blocked (0.0 when nothing synced) --
        the telemetry plane's ``ingest_pace_ms`` histogram, i.e. how
        hard ingest is riding the dispatch window."""
        if not limit or limit <= 0:
            return 0.0
        if len(self._inflight) < limit:
            return 0.0
        start = time.perf_counter()
        while len(self._inflight) >= limit:
            _, leaves = self._inflight.popleft()
            jax.block_until_ready(leaves)
            self.synced += 1
        return time.perf_counter() - start

    def drain(self) -> None:
        """Sync everything outstanding (stream flush, tests)."""
        self.pace(1)

    def clear(self) -> None:
        """Drop bookkeeping without blocking (stream destroy)."""
        self._inflight.clear()

    def invalidate(self, failed: set) -> int:
        """Forget noted frames whose outstanding leaves sit on dead
        chips (device replacement OR a single replica's failover --
        ``failed`` is device-keyed, so retiring one replica submesh
        never touches a peer's entries): ``pace`` would otherwise
        ``block_until_ready`` a buffer whose device no longer exists --
        a raise at best, a hang at worst.  Returns how many noted
        frames were dropped."""
        keep, dropped = [], 0
        for frame_id, leaves in self._inflight:
            if touches_devices(leaves, failed):
                dropped += 1
            else:
                keep.append((frame_id, leaves))
        if dropped:
            self._inflight = deque(keep)
            self.invalidated += dropped
        return dropped

    @property
    def outstanding(self) -> int:
        return len(self._inflight)

    @property
    def stats(self) -> dict:
        return {"outstanding": self.outstanding, "noted": self.noted,
                "synced": self.synced, "invalidated": self.invalidated}
