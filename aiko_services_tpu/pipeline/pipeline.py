"""Pipeline: the dataflow engine (reference: src/aiko_services/main/
pipeline.py -- 2036 LoC; this is the TPU-first redesign, not a port).

A Pipeline is an Actor hosting a DAG of PipelineElements.  Frames enter via
``process_frame`` (wire or local), walk the graph path in deterministic DFS
order accumulating outputs into the frame's ``swag`` (reference
pipeline.py:1267-1360), and responses route to a local queue or a response
topic.  Remote stages -- elements deployed in another pipeline process --
park the frame (``paused_pe_name``), forward the mapped inputs over the
fabric, and resume via ``process_frame_response`` +
``Graph.iterate_after`` (reference pipeline.py:1328-1347,1452-1455).

Differences from the reference, by design:
- single-owner frames on one event loop: no stream lock, no thread-local
  stream context (the reference's documented race area,
  pipeline.py:769-795,1239-1260);
- elements are plain objects in-process (method call, not mailbox hop);
- ``compile_element`` warm-up at stream start for jitted TPU elements;
- frame generators remain background threads (blocking IO) but hand frames
  over by message with mailbox-depth backpressure (reference
  pipeline.py:495-502).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from .codec import decode_frame_data, encode_frame_data
from .data_plane import (DATA_PLANE_MODES, PIPE_CLAIM_TIMEOUT_MS_DEFAULT,
                         PIPE_TAG, PIPE_TOKEN_CAPACITY_DEFAULT,
                         PipeSender, TensorPipeEndpoint, split_arrays)
from .definition import (PipelineDefinition, parse_pipeline_definition,
                         load_pipeline_definition, DefinitionError,
                         placement_error)
from .element import ElementContext, PipelineElement, PipelineElementLoop
from .fusion import (FUSE_MODES, FusedSegment, partition,
                     setup_compilation_cache)
from .journal import (ADOPT_LIMIT_DEFAULT, DRAIN_TIMEOUT_MS_DEFAULT,
                      JOURNAL_FSYNC_MS_DEFAULT, StreamJournal,
                      claim_adoption, decode_payload, load_journal)
from .overlap import (DEVICE_INFLIGHT_DEFAULT, TransferLedger,
                      touches_devices)
from .stages import (STAGE_INFLIGHT_DEFAULT, STAGE_PIPELINE_MODES,
                     StageScheduler)
from .stream import (Stream, Frame, StreamEvent, StreamState,
                     DEFAULT_STREAM_ID)
from ..observability import (BLACKBOX_LIMIT_DEFAULT,
                             HISTOGRAM_WINDOW_DEFAULT,
                             RECORDER_CAPACITY_DEFAULT,
                             TELEMETRY_INTERVAL_DEFAULT,
                             TRACE_CAPACITY_DEFAULT, FlightRecorder,
                             PipelineTelemetry, aggregate_traces,
                             attribute_events, decode_spans,
                             encode_spans, events_as_dicts, make_span,
                             mint_id, write_blackbox)
from ..analysis.lint import preflight as preflight_check
from ..faults import (CircuitBreaker, FaultInjected, FaultPlan,
                      wire_fault_filter)
from ..gateway.qos import QosScheduler
from ..runtime import Lease
from ..services import (Actor, ServiceFilter, ServiceTags,
                        get_service_proxy, do_discovery)
from ..services.service import SERVICE_PROTOCOL_PREFIX
from ..utils import (Graph, GraphError, get_logger, generate, load_module,
                     parse_number, process_memory_rss)

__all__ = ["Pipeline", "PROTOCOL_PIPELINE", "RemoteStage"]

_logger = get_logger("aiko.pipeline")

PROTOCOL_PIPELINE = f"{SERVICE_PROTOCOL_PREFIX}/pipeline:0"
_BACKPRESSURE_DEPTH = 32          # frames queued before a source waits
_BACKPRESSURE_SLEEP = 0.005
_GRACE_TIME_DEFAULT = 120.0
# A parked frame older than this many grace periods stops counting as
# in-flight work when the stream's grace lease fires -- the backstop
# against stages that never complete (see _stream_lease_expired).
_STALL_REAP_FACTOR = 10
_METRICS_MEMORY = False           # RSS deltas per element when True
# Undiscovered remote stages: retry with exponential backoff from the
# base up to the cap (a fixed 0.25 s forever was a silent hot loop),
# bounded by the ``remote_retry_limit`` pipeline/stream parameter
# (0 = retry forever, the pre-ISSUE-5 behavior).
_REMOTE_RETRY_BASE = 0.25
_REMOTE_RETRY_CAP = 2.0
REMOTE_RETRY_LIMIT_DEFAULT = 8
# Failure recovery (ISSUE 5): how many times one frame may be replayed
# across device replacements before it errors (``replay_limit``
# parameter, 0 = unbounded), the per-remote-stage circuit breaker
# defaults (``breaker_threshold`` consecutive failures open it,
# 0 disables; ``breaker_cooldown_ms`` before a half-open probe), and
# the live-stream overload bound (``overload_policy`` block|shed_oldest
# |shed_newest with ``overload_limit`` in-flight frames).
REPLAY_LIMIT_DEFAULT = 2
BREAKER_THRESHOLD_DEFAULT = 3
BREAKER_COOLDOWN_MS_DEFAULT = 1000.0
OVERLOAD_POLICIES = ("block", "shed_oldest", "shed_newest")
OVERLOAD_LIMIT_DEFAULT = 8
# Replicated stages (ISSUE 7): delay before the background rebuild of
# a dropped replica (``replica_rebuild_ms`` parameter, 0 = no automatic
# rebuild -- operator/autoscaler drives it), and the occupancy
# thresholds the ``replicas: auto`` control loop scales on (scale up
# the stage whose admission queue grows while its live replicas are
# busy; scale down the stage that idles).
REPLICA_REBUILD_MS_DEFAULT = 200.0
REPLICA_SCALE_UP_OCCUPANCY = 0.75
REPLICA_SCALE_DOWN_OCCUPANCY = 0.25
# Black-box dumps (ISSUE 10) are debounced per reason: a sustained
# failure episode (every frame missing its deadline) writes one dump
# per window, not one per frame on the event loop.
_BLACKBOX_COOLDOWN_S = 5.0
# Drained pipelines keep accepting (journal + hold) this long after
# announcing death, so frames in flight toward them land in the
# journal before the adopter's settle-delayed read -- then stop.
_DRAIN_RETIRE_GRACE_S = 1.0

# Stage-worker threads (pipeline/stages.py) run elements off the event
# loop; ``get_parameter`` resolution reaches the owning stream through
# this thread-local instead of the loop's _current_stream_ref.
_THREAD_STREAM = threading.local()


class RemoteStage(PipelineElement):
    """Placeholder element for a stage deployed in another pipeline
    process (reference PipelineElementDeployRemote, pipeline.py:246-258,
    858-891).  Holds the discovered service topic; the engine does the
    park/forward/resume dance."""

    def __init__(self, context, service_filter: ServiceFilter):
        super().__init__(context)
        self.service_filter = service_filter
        self.remote_topic_path: str | None = None
        # Data-plane negotiation (ISSUE 9): the peer's advertised
        # tensor-pipe endpoint ("host:port") from its registrar-record
        # ``tensor_pipe=`` tag; None = the peer speaks MQTT only and
        # forwards ride the control fabric (counted, never silent).
        self.remote_pipe: str | None = None
        self._discovery = None

    def start_discovery(self):
        self._discovery = do_discovery(
            self.pipeline.runtime, self.service_filter,
            add_handler=self._on_found, remove_handler=self._on_lost)

    def _on_found(self, record, proxy):
        self.remote_topic_path = record.topic_path
        self.remote_pipe = ServiceTags.get(record.tags, PIPE_TAG)
        self.logger.info("remote stage %s found: %s (data plane: %s)",
                         self.name, record.topic_path,
                         self.remote_pipe or "mqtt")

    def _on_lost(self, record, proxy):
        if record.topic_path == self.remote_topic_path:
            self.remote_topic_path = None
            self.remote_pipe = None
            self.logger.warning("remote stage %s lost", self.name)

    def process_frame(self, stream, **inputs):
        raise RuntimeError("RemoteStage frames are forwarded, not invoked")


class Pipeline(Actor):
    def __init__(self, definition: PipelineDefinition | dict | str,
                 name: str | None = None, runtime=None, tags=None,
                 frame_codec=None, preflight: str | None = None):
        if not isinstance(definition, PipelineDefinition):
            definition = parse_pipeline_definition(definition)
        self.definition = definition
        # Static pre-flight (ISSUE 6, analysis/): dataflow + residency
        # analysis over the definition and its element sources, BEFORE
        # the actor registers and before any device work.  A structural
        # error (unbound input, dead mapping, malformed placement,
        # impure DeviceFn, ...) raises a graph-path-qualified
        # DefinitionError here instead of failing at frame N.
        # ``preflight: strict`` makes warnings fatal too; ``off`` skips.
        # The keyword (``pipeline create --check`` -> "strict") beats
        # the definition's ``preflight`` parameter.
        preflight_report = preflight_check(definition, mode=preflight)
        # Binary data plane (ISSUE 9): unless ``data_plane: mqtt``, the
        # pipeline binds a per-process tensor-pipe endpoint BEFORE the
        # actor registers, so the registrar record advertises it as a
        # ``tensor_pipe=host:port`` tag alongside the MQTT topic.
        # Remote-stage frames then ship tensors over the pipe (raw
        # bytes, zero base64) while the control envelope stays on MQTT;
        # peers advertising no pipe negotiate down to the MQTT payload
        # path (counted).  A bind failure degrades the same way --
        # frames are never lost to the data plane being unavailable.
        mode = str(definition.parameters.get(
            "data_plane", "auto")).strip().lower()
        if mode not in DATA_PLANE_MODES:
            _logger.warning("data_plane=%r not one of %s; using auto",
                            mode, DATA_PLANE_MODES)
            mode = "auto"
        self._data_plane_mode = mode
        self._data_endpoint: TensorPipeEndpoint | None = None
        if mode != "mqtt":
            try:
                self._data_endpoint = TensorPipeEndpoint(
                    host=str(definition.parameters.get(
                        "tensor_pipe_host", "127.0.0.1")),
                    port=int(parse_number(
                        definition.parameters.get("tensor_pipe_port"),
                        0)),
                    claim_timeout_s=float(parse_number(
                        definition.parameters.get(
                            "pipe_claim_timeout_ms"),
                        PIPE_CLAIM_TIMEOUT_MS_DEFAULT)) / 1000.0,
                    capacity=int(parse_number(
                        definition.parameters.get(
                            "pipe_token_capacity"),
                        PIPE_TOKEN_CAPACITY_DEFAULT)))
            except Exception as error:
                _logger.warning("tensor-pipe data plane unavailable "
                                "(%s); frames ride MQTT", error)
        tags = list(tags or [])
        if self._data_endpoint is not None:
            tags.append(f"{PIPE_TAG}={self._data_endpoint.location}")
        # Gateway front door (ISSUE 12, gateway/server.py): ``gateway:
        # on`` binds the HTTP + WebSocket service that funnels client
        # connections into pipeline streams with per-tenant admission.
        # Bound BEFORE the actor registers -- like the tensor pipe --
        # so the registrar record advertises ``gateway=host:port``
        # and the front door is a discoverable capability of the
        # Service, per the source architecture.  Port 0 = kernel-
        # assigned, echoed on ``share["gateway_port"]``.
        self.gateway = None
        gateway_mode = str(definition.parameters.get(
            "gateway", "off")).strip().lower()
        if gateway_mode in ("on", "true", "1"):
            from ..gateway.server import GatewayServer
            self.gateway = GatewayServer(
                self,
                host=str(definition.parameters.get(
                    "gateway_host", "127.0.0.1")),
                port=int(parse_number(
                    definition.parameters.get("gateway_port"), 0)),
                session_idle_ms=float(parse_number(
                    definition.parameters.get("session_idle_ms"),
                    0.0)))
            tags.append(f"gateway={self.gateway.host}:"
                        f"{self.gateway.port}")
        # Fleet observability (ISSUE 19): ``metrics_port`` binds the
        # telemetry HTTP endpoint BEFORE the actor registers -- like
        # the gateway and the tensor pipe -- so the registrar record
        # advertises ``metrics=host:port`` and a fleet aggregator can
        # discover every member's scrape endpoint with no static
        # config.  Port 0 = kernel-assigned, echoed on
        # ``share["metrics_port"]``.
        self.metrics_server = None
        metrics_port = definition.parameters.get("metrics_port")
        if metrics_port is not None:
            telemetry_off = str(definition.parameters.get(
                "telemetry", "on")).strip().lower() in \
                ("off", "false", "0")
            if telemetry_off:
                # Binding an endpoint that can only 404 would turn
                # every fleet scrape into an error: say so at create.
                _logger.warning("metrics_port is set but telemetry=off:"
                                " endpoint not bound")
            else:
                from ..observability.exporter import MetricsServer
                metrics_host = str(definition.parameters.get(
                    "metrics_host", "127.0.0.1"))
                try:
                    self.metrics_server = MetricsServer(
                        self,
                        port=int(parse_number(metrics_port, 0)),
                        host=metrics_host)
                except OSError as error:
                    self._construction_failed()
                    raise DefinitionError(
                        f"pipeline {definition.name!r}: metrics_port="
                        f"{metrics_port!r} bind failed ({error})")
                tags.append(f"metrics={metrics_host}:"
                            f"{self.metrics_server.port}")
        # Durable stream journal + process fault domain (ISSUE 13):
        # ``journal: on`` appends each stream's recoverable state
        # (parameters, per-frame ingest payloads, delivery commits,
        # LLM committed token prefixes) to an fsync-batched journal
        # under ``journal_dir``, so a peer can ADOPT this pipeline's
        # live streams after an unclean process death -- and ``drain``
        # makes the same handoff cooperative (zero frame drop) for
        # rolling restarts.  Validated BEFORE actor registration: dead
        # config fails at create, not at the process death it was
        # configured to survive.
        self.journal: StreamJournal | None = None
        self._journal_resume: dict[tuple, list] = {}
        self._journal_lag_noted = 0.0
        self._draining = False
        self._drained = False
        self._drain_deadline = 0.0
        self._streams_adopted = 0
        self._frames_journal_replayed = 0
        self._adopt_limit = int(parse_number(
            definition.parameters.get("adopt_limit"),
            ADOPT_LIMIT_DEFAULT))
        self._drain_timeout_ms = float(parse_number(
            definition.parameters.get("drain_timeout_ms"),
            DRAIN_TIMEOUT_MS_DEFAULT))
        journal_mode = str(definition.parameters.get(
            "journal", "off")).strip().lower()
        self._journal_dir = definition.parameters.get("journal_dir")
        self._journal_dir = str(self._journal_dir) \
            if self._journal_dir else None
        if journal_mode in ("on", "true", "1"):
            if not self._journal_dir:
                self._construction_failed()
                raise DefinitionError(
                    f"pipeline {definition.name!r}: journal: on needs "
                    f"a writable journal_dir")
            try:
                os.makedirs(self._journal_dir, exist_ok=True)
                self.journal = StreamJournal(
                    os.path.join(self._journal_dir,
                                 f"{name or definition.name}.journal"),
                    fsync_ms=float(parse_number(
                        definition.parameters.get("journal_fsync_ms"),
                        JOURNAL_FSYNC_MS_DEFAULT)))
            except OSError as error:
                self._construction_failed()
                raise DefinitionError(
                    f"pipeline {definition.name!r}: journal_dir="
                    f"{self._journal_dir!r} is not writable ({error})")
        self._pipe_senders: dict[str, PipeSender] = {}
        self._pipe_token_seq = 0
        self._pipe_fallback_logged: set = set()
        # Per-stream ingest-order hold queue: a pipe frame whose
        # tensors are still in TCP flight when its envelope lands must
        # not let a LATER complete frame overtake it (see
        # _claim_for_ingest).
        self._pipe_ingest_wait: dict[str, list] = {}
        # Claim-dropped frames awaiting their MQTT re-forward: stream
        # key -> frame_id.  The ingest hold persists until the
        # re-forward arrives (or its deadline passes) so frames held
        # behind the dropped one cannot overtake its re-execution.
        self._pipe_retry_wait: dict[str, object] = {}
        self._plane_counts = {"pipe_frames": 0, "pipe_bytes": 0,
                              "mqtt_frames": 0, "mqtt_bytes": 0,
                              "fallbacks": 0, "claims_dropped": 0}
        # Everything past the gateway bind can raise a create-time
        # DefinitionError (qos parse, placement carve, graph build,
        # element load): the bound socket and its accept thread must
        # not outlive a failed construction, serving a
        # half-constructed pipeline forever.
        try:
            super().__init__(name or definition.name, PROTOCOL_PIPELINE,
                             tags=tags, runtime=runtime)
            if preflight_report is not None:
                for finding in preflight_report.findings:
                    self.logger.warning("pre-flight: %s", finding.render())
            if self.gateway is not None:
                # Failover plane (ISSUE 13): the gateway joins the
                # fabric AFTER actor registration -- it needs the
                # runtime for peer discovery and its wire-response
                # topic, neither of which exists when its socket binds.
                self.gateway.attach_runtime(self.runtime)
            self.streams: dict[str, Stream] = {}
            self._current_stream_ref: Stream | None = None
            self._current_frame_ref: Frame | None = None
            self._pipeline_parameters = dict(definition.parameters)
            # Device-resident swag accounting (pipeline/overlap.py): the
            # ``transfer_guard`` parameter sets the policy for every
            # device-resident element's event-loop execution.
            self.transfer_ledger = TransferLedger(
                definition.parameters.get("transfer_guard", "allow"))
            # Fused device-segment compilation (pipeline/fusion.py): every
            # FusedSegment built for this pipeline's streams registers here
            # (jit_stats / bench counters); the persistent XLA compile
            # cache is wired once per process, env-gated.
            self.fused_segments: list[FusedSegment] = []
            setup_compilation_cache(definition.parameters)
            # Unified QoS admission (ISSUE 12, gateway/qos.py): the ONE
            # authority the four former admission planes consult --
            # DeviceWindow pacing, StageScheduler credits, ReplicaGroup
            # slot pick, batcher admission.  Absent ``qos`` parameter =
            # None = every seam behaves exactly as before (FIFO,
            # round-robin, per-stream overload only).
            try:
                self.qos: QosScheduler | None = QosScheduler.parse(
                    definition.parameters.get("qos"))
            except (ValueError, TypeError) as error:
                # Pre-flight validates the block too (bad-parameter), but
                # ``preflight: off`` must not smuggle a malformed QoS
                # policy past create.
                raise DefinitionError(
                    f"pipeline {definition.name!r}: {error}")
            # Per-tenant SLO error budgets (ISSUE 19): objectives
            # usually live inside the qos block (``qos: {slo: ...}``);
            # a top-level ``slo`` parameter attaches the same burn
            # engine without any admission policy.  Validated here so a
            # bad block is a create-time DefinitionError even under
            # ``preflight: off``.
            slo_spec = definition.parameters.get("slo")
            if slo_spec is not None:
                from ..gateway.qos import SloTracker, slo_spec_error
                slo_problem = slo_spec_error(slo_spec)
                if slo_problem:
                    raise DefinitionError(
                        f"pipeline {definition.name!r}: {slo_problem}")
                if isinstance(slo_spec, str):
                    import json as json_module
                    slo_spec = json_module.loads(slo_spec)
                if self.qos is None:
                    self.qos = QosScheduler()
                self.qos.slo = SloTracker(slo_spec)
            self.share["slo_burn"] = {}
            self._qos_promotions = 0
            self._qos_sheds = 0
            self.share["qos_promotions"] = 0
            self.share["qos_sheds"] = 0
            # Guarded elastic fleet controller (ISSUE 20): the spec is
            # validated here -- same jax-free twin pre-flight's
            # bad-parameter rule runs -- so ``preflight: off`` cannot
            # smuggle a malformed block past create (the qos/slo/mesh
            # discipline).  Construction happens after the timers
            # below; parsing first keeps the failure create-time.
            from ..orchestration.controller import ControllerSpec
            try:
                self._controller_spec = ControllerSpec.parse(
                    definition.parameters.get("controller"),
                    definition.parameters)
            except (ValueError, TypeError) as error:
                raise DefinitionError(
                    f"pipeline {definition.name!r}: {error}")
            self.controller = None
            self._controller_timer = None
            self.share["controller_actions"] = 0
            self.share["controller_refusals"] = 0
            self.share["canary_rollbacks"] = 0
            self.share["fleet_size"] = 1
            # Per-replica element-parameter overrides (the controller's
            # canary-gated version swap): stage -> replica -> {name:
            # value}, consulted by ``PipelineElement.get_parameter``
            # through ``replica_override`` while a stage worker runs.
            self._replica_overrides: dict[str, dict[int, dict]] = {}
            # Replicated stages (ISSUE 7): stage -> (min, max) autoscale
            # bounds resolved from the placement blocks' ``replicas`` specs
            # (int N -> (N, N); "auto" -> (1, pool); {min, max} as given).
            self._replica_bounds: dict[str, tuple[int, int]] = {}
            self.stage_placement = self._build_placement()
            self.stage_scheduler = self._build_stage_scheduler()
            self._replica_failovers = 0
            self._replica_rebuilds = 0
            self.share["replica_failovers"] = 0
            self.share["replica_rebuilds"] = 0
            self.graph = self._build_graph()
            self.share["element_count"] = len(self.graph)
            self.share["streams"] = 0
            self.share["frames_processed"] = 0
            self._frames_processed = 0
            self._remote_retries = 0
            self.share["remote_stage_retries"] = 0
            self.share["data_plane_frames"] = 0
            self.share["data_plane_fallbacks"] = 0
            self.share["tensor_pipe_dropped_frames"] = 0
            # Failure recovery (ISSUE 5): fault-injection plan (None =
            # unarmed, zero hot-path work), per-remote-stage circuit
            # breakers, lazily built fallback elements, and the recovery
            # counters the chaos suite asserts on.
            self._faults: FaultPlan | None = None
            self._wire_faults_installed = False
            self.breakers: dict[str, CircuitBreaker] = {}
            self._fallback_elements: dict[str, PipelineElement] = {}
            self._frames_replayed = 0
            self._frames_shed = 0
            self._deadline_misses = 0
            self.share["frames_replayed"] = 0
            self.share["frames_shed"] = 0
            self.share["deadline_misses"] = 0
            self.share["faults_armed"] = False

            self.add_hook("pipeline.process_frame:0")
            self.add_hook("pipeline.process_element:0")
            self.add_hook("pipeline.process_element_post:0")
            self.add_hook("pipeline.process_segment:0")
            self.add_hook("pipeline.process_segment_post:0")
            self.add_hook("pipeline.process_stage:0")
            self.add_hook("pipeline.process_stage_post:0")
            self.add_hook("pipeline.stage_hop:0")
            self.add_hook("pipeline.replacement:0")
            self.add_hook("pipeline.replica_failover:0")

            # Telemetry plane (observability/): latency histograms, frame
            # traces and the export surface, fed by the hooks above.
            # ``telemetry: off`` disables it wholesale (hot-path cost drops
            # back to a no-handler hook probe per event).
            telemetry_mode = str(definition.parameters.get(
                "telemetry", "on")).strip().lower()
            if telemetry_mode in ("off", "false", "0"):
                self.telemetry = None
            else:
                self.telemetry = PipelineTelemetry(
                    self,
                    window_s=float(parse_number(
                        definition.parameters.get("telemetry_window"),
                        HISTOGRAM_WINDOW_DEFAULT)),
                    trace_capacity=int(parse_number(
                        definition.parameters.get("trace_capacity"),
                        TRACE_CAPACITY_DEFAULT)),
                    publish_interval=float(parse_number(
                        definition.parameters.get("telemetry_interval"),
                        TELEMETRY_INTERVAL_DEFAULT)))

            # Flight recorder + black-box (ISSUE 10): an always-on bounded
            # ring of typed engine events behind every seam below
            # (``recorder: off`` -> None, and every emission site is an
            # ``is not None`` no-op -- the unarmed-FaultPlan discipline).
            # ``blackbox_dir`` arms crash-dump snapshots: deadline miss,
            # replay, breaker open, replica failover and stream errors
            # write the ring tail + in-flight frame states (redacted --
            # ids/names/numbers only) to bounded JSON files that
            # ``python -m aiko_services_tpu explain <dump>`` renders.
            recorder_mode = str(definition.parameters.get(
                "recorder", "on")).strip().lower()
            if recorder_mode in ("off", "false", "0"):
                self.recorder = None
            else:
                self.recorder = FlightRecorder(int(parse_number(
                    definition.parameters.get("recorder_capacity"),
                    RECORDER_CAPACITY_DEFAULT)))
            self._blackbox_dir = definition.parameters.get(
                "blackbox_dir") or None
            if self._blackbox_dir is not None and self.recorder is None:
                # Dumps ARE ring snapshots: without the recorder the
                # configuration is dead -- say so at create, not at the
                # crash the operator configured dumps to explain.
                _logger.warning("blackbox_dir is set but recorder=off: "
                                "no black-box dumps will be written")
            self._blackbox_limit = int(parse_number(
                definition.parameters.get("blackbox_limit"),
                BLACKBOX_LIMIT_DEFAULT))
            self.share["blackbox_dumps"] = 0
            self._blackbox_dumps = 0
            self._blackbox_last: dict[str, float] = {}

            self.share["streams_adopted"] = 0
            self.share["frames_journal_replayed"] = 0
            self.share["drained"] = False

            if self.gateway is not None:
                self.share["gateway_port"] = self.gateway.port
            if self.metrics_server is not None:
                self.share["metrics_port"] = self.metrics_server.port

            # Fleet aggregator (ISSUE 19): ``fleet: on`` runs the
            # registrar-discovered collector in this process --
            # scraping every member advertising a ``metrics=`` or
            # ``gateway=`` tag -- and mounts it on the gateway's
            # ``/fleet*`` routes when the door is open.
            self.fleet_collector = None
            fleet_mode = str(definition.parameters.get(
                "fleet", "off")).strip().lower()
            if fleet_mode in ("on", "true", "1"):
                from ..observability.fleet import (
                    FLEET_SCRAPE_MS_DEFAULT, FleetCollector)
                self.fleet_collector = FleetCollector(
                    runtime=self.runtime,
                    scrape_ms=float(parse_number(
                        definition.parameters.get("fleet_scrape_ms"),
                        FLEET_SCRAPE_MS_DEFAULT)),
                    local=self)
                self.fleet_collector.start()
                if self.gateway is not None:
                    self.gateway.fleet = self.fleet_collector

            self._health_timer = None
            interval = self.definition.parameters.get("health_check_interval")
            if interval and self.stage_placement is not None:
                self._health_timer = self.runtime.engine.add_timer_handler(
                    self.check_device_health, float(interval))
            # Replica autoscale control loop (ISSUE 7): re-splits replica
            # counts from queue depth + per-replica occupancy, bounded by
            # the declared {min, max}; 0/absent = no periodic loop (the
            # ``autoscale_replicas`` method stays callable).
            self._autoscale_timer = None
            autoscale = parse_number(self.definition.parameters.get(
                "replica_autoscale_interval"), 0.0)
            if autoscale and self._has_elastic_replicas():
                self._autoscale_timer = self.runtime.engine.add_timer_handler(
                    self.autoscale_replicas, float(autoscale))

            # Fleet controller construction (ISSUE 20; spec parsed and
            # validated above).  The tick rides a GUARDED engine timer:
            # a controller bug pauses the controller, never the
            # pipeline -- and with the timer gone the fleet keeps
            # serving exactly as last tuned (do-no-harm).
            if self._controller_spec.mode != "off":
                from ..orchestration.controller import (
                    FleetController, FleetSupervisor, default_spawner)
                supervisor = None
                if self._controller_spec.fleet_max > 1 \
                        and self._controller_spec.mode == "act":
                    # Peers load fleet_definition when given, else a
                    # stripped copy of THIS definition (controller/
                    # gateway off, same journal_dir = adoptable).
                    spawn_definition = definition
                    if self._controller_spec.fleet_definition:
                        spawn_definition = load_pipeline_definition(
                            self._controller_spec.fleet_definition)
                    supervisor = FleetSupervisor(
                        default_spawner(
                            spawn_definition,
                            str(definition.parameters.get(
                                "journal_dir") or "")),
                        engine=self.runtime.engine)
                self.controller = FleetController(
                    self, self._controller_spec,
                    supervisor=supervisor)
                if supervisor is not None and self.gateway is not None:
                    # Spawned peers must TAKE load: new sessions
                    # spread least-loaded across home + peers.
                    self.gateway.balance = True
                self._controller_timer = \
                    self.runtime.engine.add_timer_handler(
                        self._controller_tick,
                        self._controller_spec.interval_ms / 1000.0)

            fault_plan = definition.parameters.get("fault_plan")
            if fault_plan:
                self.arm_faults(fault_plan)
        except BaseException:
            # The actor registered at the top of this try block: a
            # create-time failure (bad qos/slo spec, graph error) must
            # not leave a half-constructed pipeline discoverable.
            service_id = getattr(self, "service_id", None)
            if service_id is not None and self.runtime is not None:
                self.runtime.remove_service(service_id)
            fleet = getattr(self, "fleet_collector", None)
            if fleet is not None:
                fleet.stop()
                self.fleet_collector = None
            controller = getattr(self, "controller", None)
            if controller is not None \
                    and controller.supervisor is not None:
                controller.supervisor.stop_all()
            if self.metrics_server is not None:
                self.metrics_server.stop()
                self.metrics_server = None
            if self.gateway is not None:
                self.gateway.stop()
                self.gateway = None
            if self._data_endpoint is not None:
                # Same class of leak, pre-existing: the tensor-pipe
                # endpoint binds before registration too.
                self._data_endpoint.close()
                self._data_endpoint = None
            journal = getattr(self, "journal", None)
            if journal is not None:
                journal.close()
            raise

    # -- graph construction ------------------------------------------------

    def _construction_failed(self) -> None:
        """Release the pre-registration binds (gateway socket, tensor
        pipe) when ``__init__`` aborts BEFORE its guarded try block --
        a create-time DefinitionError must not leak an accepting
        socket."""
        if getattr(self, "metrics_server", None) is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.gateway is not None:
            self.gateway.stop()
            self.gateway = None
        if self._data_endpoint is not None:
            self._data_endpoint.close()
            self._data_endpoint = None

    def _build_placement(self):
        """Collect per-element ``placement`` blocks from the definition
        into one :class:`StagePlacement` over the local devices, so a
        definition file can express a multi-stage sharded pipeline
        (BASELINE config 4).  Block forms: ``{"devices": N}`` (an N-chip
        dp submesh) or ``{"mesh": {"tp": 4, ...}}``.  Elements without a
        block share all local devices (the TPUElement default).

        Frames hop between placed stages by ``StagePlacement.transfer``
        in the frame loop -- a pure ICI reshard, no host round-trip
        (the TPU analogue of the reference's remote-process deploy,
        reference pipeline.py:246-258)."""
        from .tensor import distributed_mesh_spec, ensure_distributed

        # Multi-host mesh mode (ISSUE 9): ``mesh: {hosts: N}`` (or the
        # AIKO_MESH_* env) spans one logical pipeline across hosts --
        # jax.distributed bring-up when a coordinator is configured,
        # then per-host submesh carving so same-mesh stage hops ride
        # ICI/DCN and only genuinely foreign processes pay the pipe.
        try:
            mesh_spec = distributed_mesh_spec(self.definition.parameters)
        except ValueError as error:
            raise DefinitionError(
                f"pipeline {self.definition.name!r}: {error}")
        if mesh_spec is not None:
            try:
                ensure_distributed(mesh_spec)
            except Exception as error:
                raise DefinitionError(
                    f"pipeline {self.definition.name!r}: "
                    f"jax.distributed bring-up failed: {error}")
        stages = {}
        replica_specs = {}
        stage_hosts = {}
        for element_def in self.definition.elements:
            block = element_def.placement
            if not block:
                continue
            # Same authority as the lint rule (definition.py), so a
            # 'preflight: off' definition cannot smuggle a malformed
            # block past create into the runtime placement paths.
            problem = placement_error(block)
            if problem is not None:
                raise DefinitionError(
                    f"pipeline {self.definition.name!r}: "
                    f"{element_def.name}.placement: {problem}")
            if "mesh" in block:
                stages[element_def.name] = dict(block["mesh"])
            elif "devices" in block:
                want = block["devices"]
                # ``devices: auto`` splits the pool proportionally to
                # measured per-stage cost (StagePlacement._resolve);
                # equal split until profiles exist.
                stages[element_def.name] = "auto" \
                    if isinstance(want, str) else int(want)
            else:
                # ``{"replicas": N}`` alone places nothing -- the
                # ``replicas-on-unplaced`` lint rule warns at create.
                continue
            if "replicas" in block:
                replica_specs[element_def.name] = block["replicas"]
            if "host" in block:
                stage_hosts[element_def.name] = int(block["host"])
        if not stages:
            return None
        from .tensor import StagePlacement
        placement = StagePlacement()
        replicas, replica_min = {}, {}
        pool = len(placement.devices)
        for name, spec in replica_specs.items():
            low, high = self._replica_spec_bounds(spec, pool)
            self._replica_bounds[name] = (low, high)
            replica_min[name] = low
            # Start at the floor; the control loop (and reassign after
            # recovery) grows toward the max as load demands.
            replicas[name] = low if low < high else high
        try:
            placement.assign(
                stages, replicas=replicas or None,
                replica_min=replica_min or None,
                hosts=mesh_spec["hosts"] if mesh_spec else None,
                stage_hosts=stage_hosts or None)
        except ValueError as error:
            if mesh_spec is None:
                raise               # pre-existing over-request surface
            raise DefinitionError(
                f"pipeline {self.definition.name!r}: mesh placement: "
                f"{error}")
        return placement

    @staticmethod
    def _replica_spec_bounds(spec, pool: int) -> tuple[int, int]:
        """A placement ``replicas`` spec -> (min, max) counts: int N is
        fixed at N, ``auto`` scales 1..pool, {min, max} as declared."""
        if isinstance(spec, str):
            return 1, max(1, pool)
        if isinstance(spec, dict):
            low = max(1, int(spec.get("min", 1)))
            high = int(spec.get("max", pool))
            return low, max(low, high)
        count = max(1, int(spec))
        return count, count

    def _build_stage_scheduler(self):
        """Stage-parallel execution (pipeline/stages.py): on for
        multi-stage placed pipelines unless ``stage_pipeline: off``.
        Single-stage placements have nothing to overlap with, so the
        per-element path stays exactly as before -- UNLESS the stage is
        replicated, whose frame-level data parallelism needs the
        per-replica workers and admission windows."""
        if self.stage_placement is None \
                or (len(self.stage_placement.plans) < 2
                    and not self.stage_placement.has_replicas):
            return None
        mode = str(self.definition.parameters.get(
            "stage_pipeline", "auto")).strip().lower()
        if mode not in STAGE_PIPELINE_MODES:
            self.logger.warning("stage_pipeline=%r not one of %s; "
                                "using auto", mode, STAGE_PIPELINE_MODES)
            mode = "auto"
        if mode == "off":
            return None
        depth = int(parse_number(
            self.definition.parameters.get("stage_inflight"),
            STAGE_INFLIGHT_DEFAULT))
        placement = self.stage_placement
        replicas = {stage: len(plans) for stage, plans
                    in placement.replica_plans.items()}
        return StageScheduler(list(placement.plans), depth,
                              replicas=replicas or None, qos=self.qos,
                              on_promote=self._note_promotion)

    def _cancel_health_timer(self):
        if self._health_timer is not None:
            self.runtime.engine.remove_timer_handler(self._health_timer)
            self._health_timer = None
        if self._autoscale_timer is not None:
            self.runtime.engine.remove_timer_handler(
                self._autoscale_timer)
            self._autoscale_timer = None
        if getattr(self, "_controller_timer", None) is not None:
            self.runtime.engine.remove_timer_handler(
                self._controller_timer)
            self._controller_timer = None

    def check_device_health(self, prober=None, timeout=None,
                            devices=None) -> list:
        """Probe the placement's devices (or just ``devices`` -- the
        replica-scoped probe a replicated stage's dispatch error runs,
        so one replica's probe timeout can never mark a healthy peer's
        chips suspect); on failure, recover (SURVEY.md §5.3 TPU-equiv:
        chip health checks + stage re-placement).  Failures confined to
        replicas of replicated stages take the cheap path --
        ``fail_replica`` sheds the dead replica's frames to its peers
        and the group keeps serving at N-1 -- anything wider pays for
        the full ``replace_failed_devices`` rebuild.  Returns the
        failed devices (empty when all healthy or no placement).
        Schedule periodically via the ``health_check_interval``
        pipeline parameter (seconds); probe deadline from ``timeout``
        or the ``health_probe_timeout`` pipeline parameter (seconds,
        default tpu/health.PROBE_TIMEOUT).

        An armed FaultPlan's ``device_kill``/``device_hang`` rules wrap
        the prober here -- the swappable-prober injection point, so
        chaos exercises the genuine probe -> recover -> replay path."""
        if self.stage_placement is None:
            return []
        from ..tpu.health import probe_devices
        if timeout is None:
            timeout = parse_number(
                self.get_pipeline_parameter("health_probe_timeout"), None)
        if self._faults is not None:
            prober = self._fault_prober(prober)
        pool = self.stage_placement.devices if devices is None \
            else list(devices)
        failed = probe_devices(pool, prober, timeout=timeout)
        if failed:
            # One victim at a time, RE-RESOLVED between kills: a
            # failover can escalate (all-dead rebuild reassigns, the
            # scheduler-less path full-replaces), which re-carves the
            # pool and invalidates every other victim's slot index --
            # an index resolved before the escalation would retire
            # healthy chips and leave the real dead ones placed.
            remaining = set(failed)
            while remaining:
                victims = self._replica_victims(remaining)
                if victims is None:
                    self.replace_failed_devices(remaining)
                    break
                stage, index = victims[0]
                dead = self.stage_placement.replica_devices(stage,
                                                            index)
                self.fail_replica(stage, index)
                if not dead or not (remaining - dead):
                    break
                remaining -= dead
        return failed

    def _replica_victims(self, failed) -> list[tuple] | None:
        """(stage, replica) slots covering EVERY failed device, or None
        when any failure falls outside a live replica of a replicated
        stage (the full-replace path must handle it) -- including when
        there is no stage scheduler (``stage_pipeline: off``): without
        replica admission there is no peer-shed path, so the full
        rebuild is the only recovery.  A failure spanning several
        replicas fails each -- still cheaper than stopping the world."""
        placement = self.stage_placement
        if placement is None or not placement.has_replicas \
                or self.stage_scheduler is None:
            return None
        victims = []
        covered = set()
        for stage in placement.replica_plans:
            for index in placement.live_replicas(stage):
                devices = placement.replica_devices(stage, index)
                hit = devices & set(failed)
                if hit:
                    victims.append((stage, index))
                    covered |= devices
        if not victims or set(failed) - covered:
            return None
        return victims

    def replace_failed_devices(self, failed_devices) -> None:
        """Shrink/re-place every placed stage onto surviving devices and
        tell the elements to drop plans + re-resolve weights
        (``TPUElement.on_replacement``).

        Unrecoverable failures (not enough survivors for one chip per
        stage) are terminal: the health timer stops, the condition is
        shared as ``placement_failed``, and every live stream errors --
        an operator signal, not an every-interval retry of the
        impossible."""
        from .tensor import TPUElement

        placement = self.stage_placement
        self.logger.warning("re-placing stages: %d device(s) failed",
                            len(failed_devices))
        try:
            placement.replace(failed_devices)
        except (RuntimeError, ValueError) as error:
            # ValueError: the mesh-mode hosted carve (a pinned stage's
            # host group lost too many chips) -- terminal exactly like
            # the pool running out, not an escape past the health path.
            self.logger.error("stage re-placement impossible: %s", error)
            self._cancel_health_timer()
            self.ec_producer.update("placement_failed", str(error))
            for stream_id in list(self.streams):
                stream = self.streams[stream_id]
                for frame in list(stream.frames.values()):
                    self._frame_error(stream, frame,
                                      f"placement failed: {error}")
                self._destroy_stream_now(stream_id)
            return
        for node in self.graph.nodes():
            element = node.element
            if isinstance(element, TPUElement):
                element.on_replacement()
        # Fused segments captured the old weights/devices at build time:
        # drop every stream's partition so the next frame re-plans (and
        # re-captures) against the replacement submeshes.
        for stream in self.streams.values():
            stream.fusion_plans.clear()
            stream.fusion_segments.clear()
        self.fused_segments.clear()
        # In-flight recovery (ISSUE 5): frames alive right now were
        # dispatched against the dead submeshes.  Their outstanding
        # dispatch-window leaves must never be block_until_ready'd, and
        # the frames themselves replay from their last host-visible
        # boundary instead of erroring the stream.
        failed_set = set(failed_devices)
        replay_limit = int(parse_number(
            self.get_pipeline_parameter("replay_limit"),
            REPLAY_LIMIT_DEFAULT))
        replayed = 0
        for stream in list(self.streams.values()):
            stream.device_window.invalidate(failed_set)
            for frame in list(stream.frames.values()):
                if self._replay_frame(stream, frame, failed_set,
                                      replay_limit):
                    replayed += 1
        # Replica groups track the re-placed counts (a shrunk pool may
        # have shed replicas); everything is freshly carved, so every
        # surviving slot re-admits live -- the canary discipline is for
        # the targeted rebuild path, not the stop-the-world one.
        self._reset_replica_groups()
        self._rec("replace", ms=None,
                  info={"failed": len(failed_set),
                        "generation": placement.generation,
                        "replayed": replayed})
        self.run_hook("pipeline.replacement:0",
                      lambda: {"failed": [str(d) for d in failed_devices],
                               "generation": placement.generation,
                               "replayed": replayed,
                               "stages": {name: dict(plan.mesh.shape)
                                          for name, plan
                                          in placement.plans.items()}})
        self.ec_producer.update("replacements", placement.generation)

    # -- replicated stages: failover / rebuild / autoscale (ISSUE 7) -------

    def _reset_replica_groups(self, half_open: dict | None = None) -> None:
        """Sync the scheduler's ReplicaGroups to the placement's
        current replica counts (after replace/reassign); ``half_open``
        maps stage -> iterable of slot indices that must re-admit
        behind a canary frame."""
        scheduler = self.stage_scheduler
        placement = self.stage_placement
        if scheduler is None or placement is None:
            return
        for stage, group in scheduler.groups.items():
            count = placement.replica_total(stage)
            if count:
                group.rebuild(count, (half_open or {}).get(stage, ()))

    def current_replica(self) -> tuple | None:
        """(stage, replica index) while a stage worker executes an
        element/segment for a specific replica submesh (thread-local,
        like ``current_stream``); None on the event loop and on
        unreplicated stages.  ``TPUElement.plan`` keys off it."""
        return getattr(_THREAD_STREAM, "replica", None)

    def fail_replica(self, stage: str, index: int) -> None:
        """Peer-shedding failover: retire ONE dead replica and keep the
        stage serving at N-1.  The dead slot's chips leave the pool, its
        in-flight frames drain to the surviving peers via the replay
        path (last host-visible boundary, ``replay_epoch`` voiding
        stale posts, undiscovered-remote backoff reset -- a frame
        punished for a dead replica's failures starts clean on a
        healthy one), and NOTHING else is touched: no other submesh
        rebuilds, no peer frame replays, generation unchanged.
        ``replace()``-style rebuild runs in the background after
        ``replica_rebuild_ms`` (0 disables)."""
        placement = self.stage_placement
        scheduler = self.stage_scheduler
        if placement is None:
            return
        if scheduler is None:
            # ``stage_pipeline: off`` with replicas declared: there is
            # no replica admission to shed through, but the chips are
            # still dead -- pay for the full rebuild rather than
            # silently leaving a dead submesh in the pool.
            dead = placement.replica_devices(stage, index)
            if dead:
                self.replace_failed_devices(dead)
            return
        start = time.perf_counter()
        dead = placement.drop_replica(stage, index)
        if not dead:
            return                      # unknown/already-dead slot
        # Elements whose CACHED whole-pool/shared-submesh plan spans the
        # retired chips must re-resolve (default-placed elements span
        # every local device; the replicated stage's own whole-stage
        # plan shrank) -- peers placed on their own submeshes keep
        # their plans and compiled functions untouched.
        from .tensor import TPUElement
        for node in self.graph.nodes():
            element = node.element
            if isinstance(element, TPUElement) \
                    and element._plan is not None \
                    and dead & set(element._plan.mesh.devices.flat):
                element.on_replacement()
        group = scheduler.groups.get(stage)
        if group is not None:
            group.fail(index)
        self.logger.warning(
            "stage %s replica %d failed: shedding to %d peer(s), "
            "%d chip(s) retired", stage, index,
            group.live() if group is not None else 0, len(dead))
        replay_limit = int(parse_number(
            self.get_pipeline_parameter("replay_limit"),
            REPLAY_LIMIT_DEFAULT))
        replayed = 0
        invalidated = 0
        for stream in list(self.streams.values()):
            invalidated += stream.device_window.invalidate(dead)
            for frame in list(stream.frames.values()):
                mine = frame.stage == stage and frame.stage_replica == index
                if not mine and not touches_devices(frame.swag, dead):
                    continue
                frame.remote_retries = 0    # fresh backoff on the peer
                frame.metrics.pop("remote_retries", None)
                if self._replay_frame(stream, frame, dead, replay_limit):
                    replayed += 1
        self._replica_failovers += 1
        self.share["replica_failovers"] = self._replica_failovers
        failover_ms = (time.perf_counter() - start) * 1000.0
        self.share["replica_failover_ms"] = round(failover_ms, 3)
        if self.telemetry is not None:
            self.telemetry.registry.count("replica_failovers",
                                          stage=stage)
        self._rec("failover", name=stage, ms=failover_ms,
                  info={"replica": index, "chips": len(dead),
                        "replayed": replayed})
        self._blackbox("replica_failover", detail=f"{stage}#{index}: "
                       f"{len(dead)} chip(s), {replayed} replayed")
        self.run_hook("pipeline.replica_failover:0",
                      lambda: {"stage": stage, "replica": index,
                               "failed": [str(d) for d in dead],
                               "live": group.live()
                               if group is not None else 0,
                               "replayed": replayed,
                               "window_invalidated": invalidated,
                               "ms": failover_ms})
        if group is not None and group.all_dead():
            # No peers left to shed to: the stage cannot serve at all.
            # Escalate to the full rebuild immediately (it re-fits the
            # ORIGINAL requests to the surviving pool).
            self.rebuild_replica(stage)
            return
        rebuild_ms = float(parse_number(
            self.get_pipeline_parameter("replica_rebuild_ms"),
            REPLICA_REBUILD_MS_DEFAULT))
        if rebuild_ms > 0:
            self.post_self("rebuild_replica", [stage],
                           delay=rebuild_ms / 1000.0)

    def rebuild_replica(self, stage: str) -> None:
        """Background rebuild after a failover: re-fit the ORIGINAL
        stage requests (desired replica counts included) onto the
        surviving pool and re-carve.  Every in-flight frame on rebuilt
        submeshes replays (same invalidation as ``replace()``); the
        restored slots of ``stage`` re-admit HALF-OPEN -- one canary
        frame each, breaker-style, before full re-admission
        (``replica_canary: off`` skips the canary)."""
        placement = self.stage_placement
        if placement is None or stage not in placement.replica_plans:
            return
        dead_slots = [idx for idx, plan
                      in enumerate(placement.replica_plans[stage])
                      if plan is None]
        if not dead_slots:
            # Nothing left to restore (an earlier rebuild/reassign beat
            # this post here): a reassign now would bump the generation
            # and replay every in-flight frame for nothing.
            return
        try:
            placement.reassign()
        except (RuntimeError, ValueError) as error:
            self.logger.error("replica rebuild for %s impossible: %s",
                              stage, error)
            return
        canary = str(self.get_pipeline_parameter(
            "replica_canary", "on")).strip().lower() \
            not in ("off", "false", "0")
        restored = [idx for idx in dead_slots
                    if idx < placement.replica_total(stage)]
        self._invalidate_after_reassign()
        self._reset_replica_groups(
            half_open={stage: restored} if canary else None)
        self._replica_rebuilds += 1
        self.share["replica_rebuilds"] = self._replica_rebuilds
        if self.telemetry is not None:
            self.telemetry.registry.count("replica_rebuilds",
                                          stage=stage)
        self.logger.warning(
            "stage %s rebuilt: %d replica slot(s) restored%s "
            "(generation %d)", stage, len(restored),
            " half-open behind a canary" if canary and restored else "",
            placement.generation)
        self.ec_producer.update("replacements", placement.generation)

    def _invalidate_after_reassign(self) -> None:
        """Post-reassign invalidation, shared by rebuild and autoscale:
        every stage was re-carved, so plans, fused segments and
        in-flight frames are all stale -- exactly the
        ``replace_failed_devices`` discipline minus the dead-device
        scrubbing (no chips died here) -- and minus the replay-budget
        charge: an administrative re-carve must not consume the frames'
        failure-recovery allowance (``count=False``)."""
        from .tensor import TPUElement

        for node in self.graph.nodes():
            element = node.element
            if isinstance(element, TPUElement):
                element.on_replacement()
        for stream in self.streams.values():
            stream.fusion_plans.clear()
            stream.fusion_segments.clear()
        self.fused_segments.clear()
        for stream in list(self.streams.values()):
            for frame in list(stream.frames.values()):
                self._replay_frame(stream, frame, set(), 0, count=False)

    def _has_elastic_replicas(self) -> bool:
        return any(low < high
                   for low, high in self._replica_bounds.values())

    def autoscale_replicas(self) -> dict:
        """One control-loop tick: scale UP the replicated stage whose
        admission queue grows while its live replicas run hot
        (occupancy >= REPLICA_SCALE_UP_OCCUPANCY), scale DOWN the one
        idling (every replica under REPLICA_SCALE_DOWN_OCCUPANCY, no
        queue), one step per tick, bounded by the declared {min, max}.
        Applies via ``set_replicas`` + ``reassign`` and returns the
        {stage: new count} decisions (empty = no change).  Runs
        periodically under ``replica_autoscale_interval``; callable
        directly (bench, operators)."""
        placement = self.stage_placement
        scheduler = self.stage_scheduler
        if placement is None or scheduler is None:
            return {}
        decisions: dict[str, int] = {}
        for stage, (low, high) in self._replica_bounds.items():
            if low >= high:
                continue
            group = scheduler.groups.get(stage)
            if group is None:
                continue
            live = group.live()
            occupancies = [group.occupancy(idx)
                           for idx, state in enumerate(group.states)
                           if state == "live"]
            busiest = max(occupancies, default=0.0)
            # The signal consumed, start the next tick's window fresh:
            # occupancy must describe THIS interval's load, not dilute
            # under the idle time since creation (construction +
            # first-compile alone would hold it under threshold for
            # many multiples of the tick).
            group.reset_window()
            if scheduler.waiting(stage) > 0 and live < high \
                    and busiest >= REPLICA_SCALE_UP_OCCUPANCY:
                decisions[stage] = live + 1
            elif live > low and scheduler.waiting(stage) == 0 \
                    and busiest <= REPLICA_SCALE_DOWN_OCCUPANCY:
                decisions[stage] = live - 1
        # Capacity gate for fixed-request scale-ups: without free chips
        # (or a simultaneous scale-down freeing some) the reassign
        # would shed the increment straight back -- a no-op that still
        # bumps the generation and replays every in-flight frame, every
        # tick, for as long as the load lasts.  ``auto``-request stages
        # re-split their existing allocation, so they pass freely.
        ups = {stage: count for stage, count in decisions.items()
               if count > scheduler.groups[stage].live()}
        if ups:
            allocated = sum(int(plan.mesh.devices.size)
                            for plan in placement.plans.values())
            free = len(placement.devices) - allocated
            freed = 0
            for stage, count in decisions.items():
                if count < scheduler.groups[stage].live():
                    sizes = [int(plan.mesh.devices.size) for plan
                             in placement.replica_plans.get(stage, ())
                             if plan is not None]
                    freed += min(sizes, default=0)
            for stage in ups:
                if placement._requests.get(stage) == "auto":
                    continue
                sizes = [int(plan.mesh.devices.size) for plan
                         in placement.replica_plans.get(stage, ())
                         if plan is not None]
                need = min(sizes, default=1)
                if free + freed < need:
                    del decisions[stage]
        if not decisions:
            return {}
        rollback = {stage: placement._replica_desired[stage]
                    for stage in decisions}
        for stage, count in decisions.items():
            placement.set_replicas(stage, count)
        try:
            placement.reassign()
        except (RuntimeError, ValueError) as error:
            # Restore the desired counts: leaving the phantom increment
            # behind would let the NEXT replace/rebuild re-fit carve a
            # replica this loop never reported deciding.
            for stage, count in rollback.items():
                placement.set_replicas(stage, count)
            self.logger.error("replica autoscale reassign failed: %s",
                              error)
            return {}
        self._invalidate_after_reassign()
        self._reset_replica_groups()
        for group in scheduler.groups.values():
            group.reset_window()
        self.logger.info("replica autoscale: %s (generation %d)",
                         decisions, placement.generation)
        if self.telemetry is not None:
            for stage in decisions:
                self.telemetry.registry.count("replica_autoscales",
                                              stage=stage)
        return decisions

    # -- fleet-controller actuator seams (ISSUE 20) ------------------------

    def _controller_tick(self) -> None:
        """Guarded controller tick: a controller bug pauses the
        controller and cancels its timer -- the pipeline, its streams
        and every supervised peer keep serving as last tuned
        (controller-death-safe by construction)."""
        controller = self.controller
        if controller is None:
            return
        try:
            controller.tick()
        except Exception:
            self.logger.exception(
                "fleet controller tick raised; controller paused, "
                "fleet keeps serving as tuned")
            controller.paused = True
            if self._controller_timer is not None:
                self.runtime.engine.remove_timer_handler(
                    self._controller_timer)
                self._controller_timer = None

    def set_stage_inflight(self, depth) -> bool:
        """Live re-tune of the per-stage admission window (controller
        actuator; callable by operators via ``set_parameter``-style
        wire commands too).  Deepening wakes queued waiters into the
        new credits immediately; shrinking drains naturally.  Returns
        whether anything changed."""
        scheduler = self.stage_scheduler
        depth = max(1, int(parse_number(depth, 0)))
        if scheduler is None or depth == scheduler.depth:
            return False
        previous = scheduler.depth
        scheduler.set_depth(depth)
        self._pipeline_parameters["stage_inflight"] = depth
        if depth > previous:
            for stage in scheduler.stages:
                self._pump_stage(stage)
        self.logger.info("stage_inflight: %d -> %d", previous, depth)
        return True

    def set_device_inflight(self, depth) -> bool:
        """Live re-tune of the async-dispatch overlap window.  Applies
        to the pipeline default AND every live stream that did not
        pin its own ``device_inflight`` stream parameter (a stream's
        explicit choice outlives the controller's)."""
        depth = max(0, int(parse_number(depth, 0)))
        current = int(parse_number(
            self.get_pipeline_parameter("device_inflight"),
            DEVICE_INFLIGHT_DEFAULT))
        if depth == current:
            return False
        self._pipeline_parameters["device_inflight"] = depth
        for stream in self.streams.values():
            if "device_inflight" not in stream.parameters:
                stream.device_inflight = depth
        self.logger.info("device_inflight: %d -> %d", current, depth)
        return True

    def swap_replica_version(self, stage, index, name, value,
                             canary: bool = True):
        """Set (or with ``value=None`` clear) a per-replica override
        of one element parameter -- the controller's canary-gated
        "model version" swap unit.  With ``canary`` the replica is
        demoted to half-open so its next admission is a single canary
        frame (ISSUE 7 lifecycle decides live-or-dead from that
        frame); rollback passes ``canary=False`` to restore known-good
        capacity immediately.  Returns the PREVIOUS override (None =
        none -- round-trips through rollback naturally)."""
        stage, index = str(stage), int(index)
        overrides = self._replica_overrides.setdefault(
            stage, {}).setdefault(index, {})
        old = overrides.get(name)
        if value is None:
            overrides.pop(name, None)
        else:
            overrides[name] = value
        scheduler = self.stage_scheduler
        group = None if scheduler is None \
            else scheduler.groups.get(stage)
        if canary and group is not None:
            group.reopen(index)
        self._rec("version_swap", None, None, stage, None,
                  {"replica": index, "parameter": str(name),
                   "canary": bool(canary),
                   "cleared": value is None})
        return old

    def fleetctl(self, response_topic, command, *arguments):
        """Wire-invocable fleet-controller control surface (``python
        -m aiko_services_tpu fleetctl`` publishes ``(fleetctl
        <response_topic> <command> ...)`` to our in-topic): replies on
        ``response_topic`` with the do_request pattern -- one
        ``(item_count 1)`` then one ``(fleetctl <json report>)``.
        Commands: ``status`` / ``pause`` / ``resume`` / ``force KIND
        [detail-json]`` / ``swap STAGE PARAMETER VALUE-JSON``."""
        import json

        from ..utils import generate
        command = str(command)
        controller = self.controller
        if controller is None:
            report = {"error": "no fleet controller on this pipeline "
                               "(controller: off)"}
        elif command == "status":
            report = controller.status()
        elif command == "pause":
            controller.pause()
            report = {"paused": True, "status": controller.status()}
        elif command == "resume":
            controller.resume()
            report = {"paused": False, "status": controller.status()}
        elif command == "force":
            kind = str(arguments[0]) if arguments else ""
            detail = {}
            if len(arguments) > 1:
                try:
                    detail = dict(json.loads(str(arguments[1])))
                except (ValueError, TypeError) as error:
                    detail = None
                    report = {"error": f"bad detail JSON: {error}"}
            if detail is not None:
                problem = controller.force_action(kind, **detail)
                report = {"forced": kind, "refused": problem,
                          "status": controller.status()}
        elif command == "swap":
            if len(arguments) < 3:
                report = {"error": "swap needs STAGE PARAMETER VALUE"}
            else:
                try:
                    value = json.loads(str(arguments[2]))
                except ValueError:
                    value = str(arguments[2])
                problem = controller.begin_swap(
                    str(arguments[0]), str(arguments[1]), value)
                report = {"swap": str(arguments[0]),
                          "refused": problem,
                          "status": controller.status()}
        else:
            report = {"error": f"unknown fleetctl command "
                               f"{command!r} (status|pause|resume|"
                               f"force|swap)"}
        publish = self.runtime.message.publish
        publish(str(response_topic), generate("item_count", [1]))
        publish(str(response_topic),
                generate("fleetctl", [json.dumps(report,
                                                 default=str)]))

    def replica_override(self, stage, index, name):
        """(value, found) for a per-replica parameter override --
        consulted by ``PipelineElement.get_parameter`` ahead of every
        other source while a stage worker runs replica ``index``."""
        overrides = self._replica_overrides.get(str(stage))
        if not overrides:
            return None, False
        values = overrides.get(int(index))
        if not values or name not in values:
            return None, False
        return values[name], True

    def replica_stats(self) -> dict:
        """Per-replicated-stage view the dashboard/bench read: slot
        states, per-replica in-flight + occupancy, live count, bounds,
        failover/rebuild counters."""
        placement = self.stage_placement
        scheduler = self.stage_scheduler
        if placement is None or not placement.has_replicas:
            return {}
        result: dict = {"failovers": self._replica_failovers,
                        "rebuilds": self._replica_rebuilds,
                        "stages": {}}
        failover_ms = self.share.get("replica_failover_ms")
        if failover_ms is not None:
            result["failover_ms"] = failover_ms
        for stage, plans in placement.replica_plans.items():
            entry = {"slots": [None if plan is None
                               else int(plan.mesh.devices.size)
                               for plan in plans],
                     "bounds": list(self._replica_bounds.get(
                         stage, (len(plans), len(plans))))}
            if scheduler is not None:
                group = scheduler.groups.get(stage)
                if group is not None:
                    entry.update(group.stats)
            result["stages"][stage] = entry
        return result

    def _build_graph(self) -> Graph:
        graph = Graph.traverse(self.definition.graph)
        graph.validate_acyclic()
        for node in graph.nodes():
            element_def = self.definition.element(node.name)
            context = ElementContext(node.name, element_def, self,
                                     dict(element_def.parameters))
            if element_def.deploy_local is not None:
                cls = self._load_element_class(element_def.deploy_local,
                                               node.name)
                node.element = cls(context)
            else:
                service_filter = ServiceFilter(
                    **{k: v for k, v in element_def.deploy_remote.items()
                       if k in ("name", "protocol", "transport", "owner",
                                "tags")})
                stage = RemoteStage(context, service_filter)
                stage.start_discovery()
                node.element = stage
        return graph

    def _load_element_class(self, deploy_local: dict,
                            element_name: str = "?"):
        context = (f"pipeline {self.definition.name!r}: "
                   f"{element_name}.deploy.local")
        module = load_module(deploy_local["module"])
        class_name = deploy_local.get("class_name")
        if class_name is None:
            raise DefinitionError(
                f"{context}: needs class_name (module "
                f"{deploy_local['module']!r})")
        try:
            return getattr(module, class_name)
        except AttributeError:
            raise DefinitionError(
                f"{context}: module {deploy_local['module']!r} has no "
                f"class {class_name!r}")

    # -- parameters --------------------------------------------------------

    def get_pipeline_parameter(self, name: str, default=None):
        if name in self.share:
            return self.share[name]
        return self._pipeline_parameters.get(name, default)

    def set_pipeline_parameter(self, name: str, value):
        self._pipeline_parameters[name] = value

    def set_parameter(self, name=None, value=None):
        """Wire command ``(set_parameter name value)`` -- live parameter
        update (reference pipeline.py:1585-1603).  Qualified
        ``Element.param`` targets that element's own parameters (the
        first thing ``get_parameter`` consults after stream params);
        bare names become pipeline-level parameters visible to every
        element."""
        if name is None:
            return
        name = str(name)
        if name == "fault_plan":
            # Live chaos trigger: ``-p fault_plan <json>`` from the CLI
            # / dashboard arms (or, with an empty value, disarms) the
            # fault harness on a running pipeline.
            if value in (None, "", "off", "disarm"):
                self.disarm_faults()
            else:
                self.arm_faults(value)
            return
        element_name, _, bare = name.partition(".")
        if bare and element_name in self.graph:
            self.graph.get_node(element_name).element.set_parameter(
                bare, value)
        else:
            self.set_pipeline_parameter(name, value)

    def current_stream(self) -> Stream | None:
        # Stage-worker threads pin their stream thread-locally; the
        # event loop's reference would be another frame's stream (or
        # None) while a worker is mid-element.
        stream = getattr(_THREAD_STREAM, "stream", None)
        if stream is not None:
            return stream
        return self._current_stream_ref

    def transfer_stats(self) -> dict:
        """Device-resident swag accounting: the TransferLedger counters
        plus the live streams' dispatch-window stats (bench.py reports
        ``implicit`` as ``swag_host_transfers``)."""
        stats = dict(self.transfer_ledger.stats)
        stats["window"] = {stream_id: stream.device_window.stats
                           for stream_id, stream in self.streams.items()}
        return stats

    def jit_stats(self) -> dict:
        """Compiled-function cache accounting, transfer_stats()-style:
        hit/miss/entry totals over every element JitCache and every
        fused segment's call cache, with per-element / per-segment
        breakdowns (the dashboard and bench read the totals off the
        share dict as ``jit_cache_{hits,misses,entries}``)."""
        totals = {"hits": 0, "misses": 0, "entries": 0}
        elements, segments = {}, {}
        for node in self.graph.nodes():
            cache = getattr(node.element, "jit_cache", None)
            if cache is None:
                continue
            stats = cache.stats
            elements[node.name] = stats
            for key in totals:
                totals[key] += stats[key]
        for segment in self.fused_segments:
            stats = segment.jit_cache.stats
            # Segments are stream-owned; two streams running the same
            # path each have one, so the breakdown keys by both.
            label = segment.name if segment.stream_id is None \
                else f"{segment.stream_id}:{segment.name}"
            segments[label] = segment.stats
            for key in totals:
                totals[key] += stats[key]
        totals["elements"] = elements
        totals["segments"] = segments
        return totals

    def stage_stats(self) -> dict:
        """Stage-parallel accounting: per-stage admission window state,
        occupancy over the scheduler's window, placed chip counts and
        the measured cost profile (the bench's ``stage_occupancy_*``
        keys read the occupancy values)."""
        if self.stage_scheduler is None:
            return {}
        stats = self.stage_scheduler.stats
        if self.stage_placement is not None:
            for name, plan in self.stage_placement.plans.items():
                entry = stats.setdefault(name, {})
                entry["devices"] = int(plan.mesh.devices.size)
                cost = self.stage_placement.costs.get(name)
                if cost:
                    entry["cost_ms"] = round(cost * 1000.0, 3)
        return stats

    def fusion_stats(self) -> dict:
        """Fused-segment accounting: segment/dispatch totals the bench
        reports as ``fused_segments`` / ``fused_dispatches_per_frame``."""
        return {"segments": len(self.fused_segments),
                "fused_elements": sum(len(s.nodes)
                                      for s in self.fused_segments),
                "dispatches": sum(s.calls for s in self.fused_segments),
                "broken": sum(1 for s in self.fused_segments if s.broken)}

    # -- binary data plane (ISSUE 9) ---------------------------------------

    def data_plane_stats(self) -> dict:
        """The control/data-split accounting the bench and tests read:
        frames/bytes per path, negotiated fallbacks, endpoint drops and
        expired claims, per-peer sender state."""
        stats = dict(self._plane_counts)
        stats["mode"] = self._data_plane_mode
        endpoint = self._data_endpoint
        if endpoint is not None:
            stats.update(endpoint.stats)
            self.share["tensor_pipe_dropped_frames"] = endpoint.dropped
        stats["senders"] = {location: sender.stats
                            for location, sender
                            in self._pipe_senders.items()}
        return stats

    def _pipe_sender(self, location: str) -> PipeSender:
        sender = self._pipe_senders.get(location)
        if sender is None:
            sender = self._pipe_senders[location] = PipeSender(location)
        return sender

    def _next_pipe_token(self) -> str:
        # Unique across processes: the service topic path is unique per
        # (host, pid, service), the counter per forward attempt.
        self._pipe_token_seq += 1
        return f"{self.topic_path}#{self._pipe_token_seq}"

    def _count_plane(self, pipe_bytes, envelope_len: int) -> None:
        counts = self._plane_counts
        if pipe_bytes is None:
            counts["mqtt_frames"] += 1
            counts["mqtt_bytes"] += int(envelope_len)
        else:
            counts["pipe_frames"] += 1
            counts["pipe_bytes"] += int(pipe_bytes) + int(envelope_len)
            self.share["data_plane_frames"] = counts["pipe_frames"]

    def _count_pipe_fallback(self, where: str, reason: str) -> None:
        """A frame whose tensors were pipe-eligible rode MQTT instead
        (peer advertises no pipe, send failed, breaker open): counted
        on the share dict and the telemetry plane, logged once per
        (site, reason) so a degraded data plane is VISIBLE without
        spamming every frame."""
        self._plane_counts["fallbacks"] += 1
        self.share["data_plane_fallbacks"] = \
            self._plane_counts["fallbacks"]
        # Exposition rides the metrics_text gauge refresh (like
        # data_plane_frames) -- registering the same name as a counter
        # TOO would emit duplicate samples and invalidate the scrape.
        self._rec("pipe_fallback", name=where,
                  info={"reason": reason})
        mark = (where, reason)
        if mark not in self._pipe_fallback_logged:
            self._pipe_fallback_logged.add(mark)
            self.logger.warning("data plane: %s: %s -- tensors ride "
                                "MQTT (counted, see "
                                "data_plane_fallbacks)", where, reason)

    def _pipe_ship(self, pipe_location, frame_data: dict, header: dict,
                   where: str):
        """Try to ship ``frame_data``'s arrays over the tensor pipe to
        ``pipe_location``; on success the header grows the claim token
        + key list and the returned body holds only the residue for
        the MQTT envelope.  Any failure returns the FULL frame_data --
        the MQTT path is the always-correct fallback, so a data-plane
        problem costs bytes, never frames.  Returns (body, pipe_bytes
        or None)."""
        arrays = split_arrays(frame_data)
        if not arrays:
            return frame_data, None
        if not pipe_location:
            self._count_pipe_fallback(
                where, "peer advertises no tensor pipe")
            return frame_data, None
        sender = self._pipe_sender(str(pipe_location))
        token = self._next_pipe_token()
        sent = sender.send(token, arrays)
        if sent is None:
            self._count_pipe_fallback(
                where, f"pipe send to {pipe_location} failed or "
                       f"breaker open")
            return frame_data, None
        header["pipe_token"] = token
        header["pipe_keys"] = sorted(arrays)
        body = {key: value for key, value in frame_data.items()
                if key not in arrays}
        return body, sent

    def _count_claim_dropped(self, token, command: str) -> None:
        self._plane_counts["claims_dropped"] += 1
        self._rec("claim_drop", name=str(token),
                  info={"command": command})
        self.logger.warning(
            "data plane: %s token %s expired with tensors missing -- "
            "dropping the envelope (sender recovers via deadline/"
            "breaker, exactly as for a dropped wire frame)",
            command, token)

    def _claim_for_ingest(self, stream_dict: dict,
                          frame_data: dict) -> dict | None:
        """Pair an inbound ``process_frame`` envelope with its pipe
        tensors.  Returns the claimed arrays ({} when the frame has no
        pipe token) or None when the envelope was handled elsewhere --
        deferred behind the endpoint watch, queued behind an earlier
        still-waiting frame of the same stream (ingest order is a
        per-stream contract: the pipe and the envelope race, and a
        complete frame must not overtake an incomplete predecessor),
        or dropped after the claim timeout."""
        stream_key = str(stream_dict.get("stream_id",
                                         DEFAULT_STREAM_ID))
        waiting = self._pipe_ingest_wait.get(stream_key)
        token = stream_dict.get("pipe_token")
        if waiting is not None:
            retry_id = self._pipe_retry_wait.get(stream_key)
            if retry_id is not None and not token \
                    and str(stream_dict.get("frame_id")) == str(retry_id):
                # The awaited MQTT re-forward of the claim-dropped
                # head: ingest it NOW, then release the envelopes held
                # behind it in arrival order (posted, so they ingest
                # after this frame).
                del self._pipe_retry_wait[stream_key]
                for held_dict, held_data in \
                        self._pipe_ingest_wait.pop(stream_key, None) \
                        or []:
                    self.post_self("process_frame",
                                   [held_dict, held_data])
                return {}
            # An earlier frame of this stream is still waiting for its
            # tensors: hold THIS envelope (tokened or not) behind it.
            waiting.append((stream_dict, frame_data))
            return None
        if not token:
            return {}
        keys = [str(key) for key in
                (stream_dict.get("pipe_keys") or [])]
        endpoint = self._data_endpoint
        if endpoint is None:
            # The sender saw our advertised tag but the endpoint is
            # gone (mode flipped live): the tensors are unreachable.
            self._count_claim_dropped(token, "process_frame")
            return None
        claimed = endpoint.claim(token, keys)
        if claimed is not None:
            return claimed
        if stream_dict.get("pipe_deferred"):
            # Second pass (watch fired at the timeout, tensors still
            # missing -- the pipe died with them in a kernel buffer).
            # Tell the origin so it RE-FORWARDS this frame over MQTT:
            # a data-plane loss must cost latency, never the frame.
            self._count_claim_dropped(token, "process_frame")
            response_topic = stream_dict.get("response_topic")
            if response_topic:
                header = {"stream_id": stream_dict.get(
                              "stream_id", DEFAULT_STREAM_ID),
                          "frame_id": stream_dict.get("frame_id"),
                          "okay": False, "pipe_retry": True,
                          "diagnostic": "tensor pipe payload missing "
                                        "(claim timeout)"}
                self.runtime.message.publish(
                    response_topic,
                    generate("process_frame_response", [header, {}]))
                # The origin will re-forward this frame over MQTT:
                # keep the stream's ingest hold until it lands, else
                # complete frames held behind this one would overtake
                # the re-execution.  Deadline-bounded -- an origin
                # that never re-forwards (died, retry budget spent)
                # must not wedge the stream.
                frame_id = stream_dict.get("frame_id")
                self._pipe_ingest_wait.setdefault(stream_key, [])
                self._pipe_retry_wait[stream_key] = frame_id
                self.runtime.engine.add_oneshot_timer(
                    lambda: self._pipe_retry_expired(stream_key,
                                                     frame_id),
                    max(1.0, endpoint.claim_timeout_s))
            return None
        stream_dict["pipe_deferred"] = True
        self._pipe_ingest_wait[stream_key] = []
        endpoint.watch(
            token, keys,
            lambda: self.post_self("ingest_pipe_ready",
                                   [stream_key, stream_dict,
                                    frame_data]))
        return None

    def _pipe_retry_expired(self, stream_key, frame_id) -> None:
        """Deadline for a requested MQTT re-forward that never arrived
        (origin died, retry budget spent): release the ingest hold so
        the stream keeps serving -- the dropped frame belongs to the
        origin's deadline/breaker machinery now."""
        if self._pipe_retry_wait.get(str(stream_key)) != frame_id:
            return
        del self._pipe_retry_wait[str(stream_key)]
        held = self._pipe_ingest_wait.pop(str(stream_key), None) or []
        for held_dict, held_data in held:
            self.process_frame(held_dict, held_data)

    def ingest_pipe_ready(self, stream_key, stream_dict, frame_data):
        """Continuation: the head waiting frame's pipe tensors arrived
        (or its claim timed out).  Ingest it first, then replay the
        envelopes held behind it in arrival order -- an entry that is
        itself incomplete re-establishes the hold and the remainder
        queues behind it again."""
        held = self._pipe_ingest_wait.pop(str(stream_key), None) or []
        self.process_frame(stream_dict, frame_data)
        for held_dict, held_data in held:
            self.process_frame(held_dict, held_data)

    def _claim_pipe_response(self, stream_dict: dict,
                             frame_data: dict) -> dict | None:
        """The response twin of ``_claim_for_ingest``.  Responses need
        no ordering hold: a parked frame resumes by id whenever ITS
        response completes."""
        token = stream_dict.get("pipe_token")
        if not token:
            return {}
        keys = [str(key) for key in
                (stream_dict.get("pipe_keys") or [])]
        endpoint = self._data_endpoint
        if endpoint is None:
            self._count_claim_dropped(token, "process_frame_response")
            return None
        claimed = endpoint.claim(token, keys)
        if claimed is not None:
            return claimed
        if stream_dict.get("pipe_deferred"):
            # The RESPONSE's tensors died with the pipe: re-forward the
            # still-parked frame over MQTT (the remote re-executes --
            # the same idempotency the wire-retry paths already
            # assume); past the retry bound, the deadline/breaker
            # machinery recovers it like any dropped response.
            self._count_claim_dropped(token, "process_frame_response")
            self._retry_parked_over_mqtt(stream_dict)
            return None
        stream_dict["pipe_deferred"] = True
        endpoint.watch(
            token, keys,
            lambda: self.post_self("process_frame_response",
                                   [stream_dict, frame_data]))
        return None

    def _retry_parked_over_mqtt(self, stream_dict: dict) -> None:
        """A pipe-shipped payload for a parked frame never arrived:
        re-forward the frame over the MQTT payload path, once per
        frame (``pipe_retries``) -- past that, the deadline/breaker
        machinery owns recovery."""
        stream = self.streams.get(str(stream_dict.get(
            "stream_id", DEFAULT_STREAM_ID)))
        frame = stream.frames.get(int(parse_number(
            stream_dict.get("frame_id"), -1))) \
            if stream is not None else None
        if frame is None or frame.paused_pe_name is None \
                or frame.paused_pe_name not in self.graph:
            return
        node = self.graph.get_node(frame.paused_pe_name)
        if not isinstance(node.element, RemoteStage):
            return
        if frame.metrics.get("pipe_retries", 0) >= 1:
            return
        frame.metrics["pipe_retries"] = \
            frame.metrics.get("pipe_retries", 0) + 1
        self._count_pipe_fallback(
            f"re-forward to {node.name}",
            "pipe payload missing; resending over MQTT")
        self._forward_frame(stream, frame, node, force_mqtt=True)

    def _upload_claimed(self, stream_id, claimed: dict) -> dict:
        """Claimed pipe tensors land host-side zero-copy; when the
        stream's head is a PLACED stage, ``device_put`` them straight
        onto its submesh here -- the upload overlaps the walk dispatch
        instead of serializing at the first stage hop (which skips
        leaves already resident)."""
        placement = self.stage_placement
        if placement is None:
            return claimed
        stream = self.streams.get(str(stream_id))
        head = stream.graph_path if stream is not None \
            and stream.graph_path else \
            (self.graph.heads[0].name if self.graph.heads else None)
        if head not in placement.plans:
            return claimed
        try:
            return placement.transfer(claimed, head)
        except Exception:
            self.logger.exception("data plane: device_put of claimed "
                                  "tensors onto stage %r failed; "
                                  "leaving them host-side", head)
            return claimed

    # -- fault harness + failure recovery (ISSUE 5) ------------------------

    def arm_faults(self, spec=None) -> None:
        """Arm a FaultPlan: ``spec`` is a rules list / {"seed", "rules"}
        dict / JSON string (see faults/plan.py for the points).  Wire-
        callable -- ``(arm_faults <json>)`` -- so the dashboard or CLI
        triggers chaos against a LIVE pipeline.  Re-arming replaces the
        previous plan; wire rules install a filter on the loopback
        broker (the only transport that supports them)."""
        try:
            plan = FaultPlan.parse(spec)
        except (ValueError, TypeError) as error:
            self.logger.error("arm_faults: bad plan: %s", error)
            return
        self._remove_wire_faults()
        self._faults = plan
        self.logger.warning("fault plan ARMED: %d rule(s), seed=%d",
                            len(plan.rules), plan.seed)
        if plan.has_wire_rules:
            broker = self._loopback_broker()
            if broker is None:
                self.logger.warning(
                    "fault plan has wire rules but the transport is not "
                    "loopback; wire faults will not fire")
            else:
                broker.set_fault_filter(
                    wire_fault_filter(plan, broker.publish_direct))
                self._wire_faults_installed = True
        self.ec_producer.update("faults_armed", True)

    def disarm_faults(self) -> None:
        """Disarm the plan: every injection point returns to its
        unarmed (zero-work) path."""
        self._remove_wire_faults()
        if self._faults is not None:
            self.logger.warning("fault plan disarmed")
        self._faults = None
        self.ec_producer.update("faults_armed", False)

    def _loopback_broker(self):
        message = getattr(self.runtime, "message", None)
        return getattr(message, "_broker", None)

    def _remove_wire_faults(self) -> None:
        if not self._wire_faults_installed:
            return
        broker = self._loopback_broker()
        if broker is not None:
            broker.set_fault_filter(None)
        self._wire_faults_installed = False

    def fault_stats(self) -> dict:
        """The chaos/recovery surface tests and the dashboard read:
        plan counters + trace (blast radius), breaker states, and the
        recovery counters."""
        stats = {"armed": self._faults is not None,
                 "frames_replayed": self._frames_replayed,
                 "frames_shed": self._frames_shed,
                 "deadline_misses": self._deadline_misses,
                 "breakers": {name: breaker.stats
                              for name, breaker in self.breakers.items()}}
        if self._faults is not None:
            stats["plan"] = self._faults.stats
        return stats

    def _fault_target_devices(self, target) -> set:
        """Resolve a device-fault rule's target: a placed stage name
        (its current submesh), ``stage#<replica>`` for ONE replica's
        submesh of a replicated stage, ``device:<index>`` into the
        placement pool, or None for every placed device."""
        placement = self.stage_placement
        if placement is None:
            return set()
        if target is None:
            return set(placement.devices)
        target = str(target)
        if target in placement.plans:
            return placement.stage_devices(target)
        if "#" in target:
            stage, _, index = target.partition("#")
            if stage in placement.replica_plans:
                try:
                    return placement.replica_devices(stage, int(index))
                except (ValueError, IndexError):
                    return set()
        if target.startswith("device:"):
            try:
                return {placement.devices[int(target[7:])]}
            except (ValueError, IndexError):
                return set()
        return set()

    def _fault_prober(self, prober):
        """Wrap the health prober per the armed plan: ``device_kill``
        targets report dead, ``device_hang`` targets sleep through the
        probe deadline.  Rules fire ONCE per health check (count
        semantics: one rule firing = one failure event)."""
        plan = self._faults
        dead: set = set()
        hung: list = []
        for rule in plan.fire_point("device_kill"):
            dead |= self._fault_target_devices(rule.target)
        for rule in plan.fire_point("device_hang"):
            hung.append((self._fault_target_devices(rule.target),
                         rule.delay_ms))
        if not dead and not hung:
            return prober
        from ..tpu.health import default_prober
        base = prober or default_prober
        self.logger.warning("injected device fault: %d dead, %d hung",
                            len(dead), len(hung))

        def wrapped(device):
            if device in dead:
                return False
            for devices, delay_ms in hung:
                if device in devices:
                    time.sleep(delay_ms / 1000.0)
            return base(device)

        return wrapped

    def _inject_element_fault(self, node_name: str, stream_id) -> None:
        """Armed-plan probe at an element dispatch site (sync walk,
        stage worker, async submit).  ``element_hang`` sleeps in place
        -- a chip gone quiet; ``element_raise`` raises FaultInjected --
        the XLA dead-chip dispatch error surface.  Callers' existing
        exception paths (and the dispatch-error recovery probe) handle
        the rest, which is the point: chaos runs the REAL paths."""
        faults = self._faults
        if faults is None:          # disarmed between check and call
            return
        rule = faults.should("element_hang", target=node_name,
                             stream=stream_id)
        if rule is not None:
            time.sleep(rule.delay_ms / 1000.0)
        rule = faults.should("element_raise", target=node_name,
                             stream=stream_id)
        if rule is not None:
            raise FaultInjected(
                f"injected device failure at {node_name}")

    def _inject_segment_fault(self, segment_name: str, stream_id) -> None:
        """Armed-plan probe at a fused-segment dispatch site (event
        loop and stage-worker paths share it)."""
        faults = self._faults
        if faults is not None \
                and faults.should("segment_fail", target=segment_name,
                                  stream=stream_id) is not None:
            raise FaultInjected(
                f"injected segment failure at {segment_name}")

    def _recover_after_dispatch_error(self, stream: Stream,
                                      frame: Frame) -> bool:
        """A dispatch raised on a placed pipeline: before declaring the
        frame dead, probe the chips -- on real hardware XLA raising at
        dispatch IS how chip loss presents.  When the probe finds
        failures, ``replace_failed_devices`` has already re-placed the
        stages and replayed (or error-bounded) every in-flight frame,
        THIS one included; the caller must then skip its own
        _frame_error.  Healthy probe -> False -> normal error path (a
        code bug is not a chip loss).

        On a replicated stage the probe is SCOPED to the frame's own
        replica submesh (ISSUE 7): the dispatch raised there, so that
        is where the evidence points -- and a hung chip's probe
        timeout must never mark a healthy peer's chips suspect (the
        periodic ``health_check_interval`` probe still walks the full
        pool, so failures elsewhere are found on their own clock, not
        blamed on this frame)."""
        if self.stage_placement is None:
            return False
        scoped = None
        if frame.stage is not None and frame.stage_replica is not None:
            devices = self.stage_placement.replica_devices(
                frame.stage, frame.stage_replica)
            if devices:
                scoped = list(devices)
        try:
            failed = self.check_device_health(devices=scoped)
        except Exception:
            self.logger.exception("post-dispatch-error health check "
                                  "failed")
            return False
        return bool(failed)

    def _replay_frame(self, stream: Stream, frame: Frame, failed: set,
                      replay_limit: int, count: bool = True) -> bool:
        """Re-admit one in-flight frame after a device replacement.

        The replay frontier is the frame's last host-visible boundary:
        elements whose outputs the frame already accepted
        (``frame.completed``) never re-execute; swag device leaves on
        dead chips are fetched to host when still reachable (re-uploaded
        to the replacement submeshes by the replayed walk's normal
        hops/puts) or dropped.  Bounded by ``replay_limit`` per frame;
        over it, the frame errors instead of looping.  ``count=False``
        is the ADMINISTRATIVE replay (autoscale re-split, background
        replica rebuild): no chips failed, so the engine's own re-carve
        must not consume the frame's failure-recovery budget -- under
        sustained load consecutive scale-up ticks would otherwise error
        the very backlog they exist to absorb.  Returns True when the
        frame was scheduled for replay."""
        node = self.graph.get_node(frame.paused_pe_name) \
            if frame.paused_pe_name is not None \
            and frame.paused_pe_name in self.graph else None
        if node is not None and isinstance(node.element, RemoteStage):
            # The remote round trip is unaffected by LOCAL chip death;
            # just scrub stranded swag so the resume survives.
            self._scrub_swag(frame, failed)
            return False
        if count:
            frame.replays += 1
            if replay_limit and frame.replays > replay_limit:
                # Per-frame failure: the over-budget FRAME errors;
                # sibling frames still within budget keep their replays
                # (and the stream) alive.
                self._frame_fail(
                    stream, frame,
                    f"replay limit ({replay_limit}) exceeded after "
                    f"device replacement")
                return False
        # Critical-path ``replay`` bucket: time since the frame last
        # made progress (the end of its most recently FINISHED element
        # run, or the start of the one still in flight) -- the work
        # this replay voids.  Completed runs stay billed to
        # ``compute``; the wall time covers both attempts, so buckets
        # still sum to e2e, not above it.
        progress = []
        for key, value in frame.metrics.items():
            if key.endswith("_time_start"):
                elapsed = frame.metrics.get(f"{key[:-11]}_time")
                progress.append(float(value)
                                + float(elapsed or 0.0))
        if progress:
            lost_ms = (time.perf_counter() - max(progress)) * 1000.0
            if lost_ms > 0.0:
                frame.metrics["replay_lost_ms"] = \
                    frame.metrics.get("replay_lost_ms", 0.0) + lost_ms
        # Stale-ify every in-flight continuation of the PREVIOUS
        # attempt: worker/async completion posts carry the epoch they
        # were submitted under and are discarded on mismatch.
        frame.replay_epoch += 1
        frame.paused_pe_name = None
        # ok=None: a replayed frame is yanked, not judged -- a
        # half-open slot whose canary it was keeps waiting for a REAL
        # verdict (unless this stage already completed, which
        # _release_stage upgrades to success).
        self._release_stage(stream, frame, ok=None)
        self._scrub_swag(frame, failed)
        resume_at = None
        for path_node in self._stream_path(stream):
            if path_node.name not in frame.completed:
                resume_at = path_node.name
                break
        self._count_replay(stream)
        frame.metrics["replays"] = frame.replays
        self._rec("replay", stream.stream_id, frame.frame_id,
                  resume_at, info={"attempt": frame.replays,
                                   "counted": count})
        if count:
            # Administrative replays (autoscale re-split, background
            # rebuild) touch every in-flight frame -- only genuine
            # failure replays are worth a dump each.
            self._blackbox("replay", stream.stream_id, frame.frame_id,
                           detail=f"resume at {resume_at} "
                                  f"(attempt {frame.replays})")
        self.logger.warning(
            "stream %s frame %s: replaying at %s (attempt %d) after "
            "device replacement", stream.stream_id, frame.frame_id,
            resume_at, frame.replays)
        if resume_at is None:
            self._frame_done(stream, frame, None)
            return True
        self.post_self("retry_frame_at",
                       [stream.stream_id, frame, resume_at])
        return True

    def _scrub_swag(self, frame: Frame, failed: set) -> None:
        """Invalidate swag device leaves stranded on dead chips: values
        still fetchable come back as host copies (ONE counted ledger
        fetch each -- the engine-initiated sanctioned transfer), values
        whose buffers died with the chip are dropped so the replayed
        walk fails cleanly on missing inputs rather than dispatching a
        dead buffer."""
        dropped = 0
        for key in list(frame.swag):
            value = frame.swag[key]
            if not touches_devices(value, failed):
                continue
            try:
                frame.swag[key] = self.transfer_ledger.fetch(value)
            except Exception:
                frame.swag.pop(key, None)
                dropped += 1
        if dropped:
            frame.metrics["replay_dropped_keys"] = \
                frame.metrics.get("replay_dropped_keys", 0) + dropped

    # -- deadlines + overload shedding -------------------------------------

    def _count_replay(self, stream: Stream) -> None:
        self._frames_replayed += 1
        self.share["frames_replayed"] = self._frames_replayed
        if self.telemetry is not None:
            self.telemetry.registry.count("frames_replayed")

    def _count_shed(self, stream: Stream) -> None:
        self._frames_shed += 1
        self.share["frames_shed"] = self._frames_shed
        if self.telemetry is not None:
            self.telemetry.registry.count("frames_shed")

    def _deadline_fail(self, stream: Stream, frame: Frame) -> None:
        """A frame blew its ``frame_deadline_ms``: cancel remaining
        work (the frame leaves stream.frames, so any in-flight
        continuation post goes stale) and deliver a deadline error in
        its reorder slot.  The STREAM stays alive -- an SLO miss on one
        frame is not a stream failure.  A frame parked at a remote
        stage counts the miss against that stage's circuit breaker:
        the remote never answered in time."""
        self._deadline_misses += 1
        self.share["deadline_misses"] = self._deadline_misses
        if self.telemetry is not None:
            self.telemetry.registry.count("deadline_misses")
        parked_at = frame.paused_pe_name
        if parked_at is not None and parked_at in self.graph:
            node = self.graph.get_node(parked_at)
            if isinstance(node.element, RemoteStage):
                breaker = self._stage_breaker(parked_at)
                if breaker is not None:
                    self._breaker_failure(parked_at, breaker,
                                          stream.stream_id,
                                          frame.frame_id)
        self._rec("deadline", stream.stream_id, frame.frame_id,
                  parked_at)
        self._blackbox("deadline_miss", stream.stream_id,
                       frame.frame_id,
                       detail=f"parked at {parked_at}"
                       if parked_at else "")
        frame.metrics["deadline_missed"] = True
        frame.replay_epoch += 1         # stale-ify late worker posts
        self._frame_fail(stream, frame,
                         f"deadline exceeded "
                         f"({stream.deadline_ms:.0f} ms)")

    def expire_frame(self, stream_id, frame_id, frame_ref=None):
        """Continuation posted at ingest for deadline-bearing frames:
        fires once at the deadline and fails the frame wherever it is
        -- walking, queued for admission, or parked at an async/worker/
        remote stage that will never answer.  This is what guarantees
        'completes or errors within its deadline' even for parks."""
        stream = self.streams.get(str(stream_id))
        frame = stream.frames.get(int(frame_id)) \
            if stream is not None else None
        if frame is None or frame is not frame_ref \
                or frame.deadline is None:
            return
        remaining = frame.deadline - time.monotonic()
        if remaining > 0:               # timer fired marginally early
            self.post_self("expire_frame",
                           [stream_id, frame_id, frame],
                           delay=remaining + 0.005)
            return
        if self._draining:
            # Deadline errors are deliveries; a draining pipeline
            # parks the frame for adoption instead (see
            # ``_past_deadline``).
            return
        self._deadline_fail(stream, frame)

    def _shed_for_overload(self, stream: Stream) -> bool:
        """Queue-depth shedding at ingest for live streams.  Returns
        True when the INCOMING frame should be refused (shed_newest, or
        shed_oldest with no cancellable victim); shed_oldest cancels
        the oldest frame still waiting for stage admission -- the only
        frames whose work can be cancelled without abandoning running
        compute -- which also frees its credit-window pressure."""
        if stream.overload_policy == "block" or not stream.overload_limit \
                or stream.in_flight < stream.overload_limit:
            return False
        if stream.overload_policy == "shed_oldest":
            victim = min(
                (f for f in stream.frames.values()
                 if f.stage_waiting is not None),
                key=lambda f: f.frame_id, default=None)
            if victim is not None:
                self._count_shed(stream)
                victim.metrics["shed"] = True
                self._rec("shed", stream.stream_id, victim.frame_id,
                          info={"policy": stream.overload_policy})
                self._frame_fail(
                    stream, victim,
                    f"shed: overload ({stream.overload_policy}, "
                    f"{stream.in_flight} in flight)")
                return False
        return True

    def _shed_incoming(self, stream: Stream, frame: Frame) -> None:
        """Refuse an incoming frame under overload: it still takes its
        delivery slot (in-order contract) and responds with a shed
        error immediately."""
        self._count_shed(stream)
        frame.metrics["shed"] = True
        self._rec("shed", stream.stream_id, frame.frame_id,
                  info={"policy": stream.overload_policy,
                        "incoming": True})
        self._frame_fail(stream, frame,
                         f"shed: overload ({stream.overload_policy}, "
                         f"{stream.in_flight} in flight)")

    # -- unified QoS admission (ISSUE 12, gateway/qos.py) ------------------

    def _stamp_qos(self, stream: Stream, frame: Frame) -> None:
        """Resolve the frame's tenant/class from its stream and open
        the scheduler's in-flight accounting (closed exactly once by
        ``_qos_done`` on any completion path).  The ingest sequence is
        the rank tiebreak that keeps same-class (and per-stream)
        arrival order."""
        frame.tenant = stream.tenant
        frame.qos_class = stream.qos_class
        frame.qos_wait_start = time.monotonic()
        if self.qos is None:
            return
        frame.qos_seq = self.qos.next_seq()
        frame.qos_open = True
        self.qos.frame_started(frame.tenant)

    def _qos_done(self, frame: Frame) -> None:
        """Close the scheduler's in-flight accounting for a frame
        (idempotent -- the flag flips once)."""
        if frame.qos_open:
            frame.qos_open = False
            if self.qos is not None:
                self.qos.frame_finished(frame.tenant)

    def _device_limit(self, stream: Stream) -> int:
        """The stream's effective dispatch-window depth: per-class caps
        from the QoS policy tighten the resolved ``device_inflight``
        (plane 1 of the unified scheduler)."""
        if self.qos is None:
            return stream.device_inflight
        return self.qos.device_limit(stream.qos_class,
                                     stream.device_inflight)

    def _qos_shed_for_overload(self, stream: Stream,
                               frame: Frame) -> bool:
        """Pipeline-wide QoS shedding at ingest (``max_inflight`` in
        the qos block): when the engine is over budget, shed the WORST
        victim across ALL streams -- over-budget tenants first, then
        the lowest class, then the oldest -- which may be the incoming
        frame itself (returns True: refuse it) or a queued frame of
        another stream (failed in ITS reorder slot; the incoming frame
        proceeds).  Only admission-queued frames are cancellable
        victims, exactly like ``shed_oldest``."""
        if self.qos is None or not self.qos.overloaded():
            return False
        # Severity is the (over_budget, class_rank) prefix; the seq
        # component of shed_key only picks WHICH victim among the
        # worst group (oldest first).  Only a victim STRICTLY worse
        # than the incoming frame sheds -- an in-budget tenant must
        # never shed its own frames just because the engine is busy
        # (the stage credits bound its memory; blocking is the right
        # backpressure there).  With no worse victim, the incoming
        # frame itself sheds only when ITS tenant is over budget.
        budgets = self.qos.budget_snapshot()
        incoming_key = self.qos.shed_key(frame, budgets)
        victim, victim_stream, victim_key = None, stream, None
        for other in self.streams.values():
            for candidate in other.frames.values():
                if candidate.stage_waiting is None:
                    continue
                key = self.qos.shed_key(candidate, budgets)
                if key[:2] <= incoming_key[:2]:
                    continue                # not strictly worse
                if victim_key is None or key > victim_key:
                    victim, victim_stream, victim_key = \
                        candidate, other, key
        if victim is None:
            if not incoming_key[0]:         # in budget: admit
                return False
            victim, victim_stream = frame, stream
        self.qos.count_shed(victim.tenant)
        if self.telemetry is not None:
            # Resolved entry name, not the raw string: label
            # cardinality stays bounded by LAZY_TENANT_CAP.
            self.telemetry.registry.count(
                "qos_sheds", tenant=self.qos.tenant(victim.tenant).name,
                cls=str(victim.qos_class))
        self._qos_sheds += 1
        self.share["qos_sheds"] = self._qos_sheds
        if victim is frame:
            return True
        self._count_shed(victim_stream)
        victim.metrics["shed"] = True
        self._rec("shed", victim_stream.stream_id, victim.frame_id,
                  info={"policy": "qos", "tenant": victim.tenant,
                        "cls": victim.qos_class})
        self._frame_fail(
            victim_stream, victim,
            f"shed: qos overload ({self.qos.inflight_total} in "
            f"flight, tenant {victim.tenant})")
        return False

    def _note_promotion(self, stream_id, frame: Frame) -> None:
        """A frame's near-deadline promotion decided a waiter pop
        (StageScheduler ``on_promote``, fired once per frame): count
        it and put it on the ring next to the admit it caused."""
        self._qos_promotions += 1
        self.share["qos_promotions"] = self._qos_promotions
        if self.telemetry is not None:
            self.telemetry.registry.count(
                "qos_promotions", cls=str(frame.qos_class))
        self._rec("gw_promote", stream_id, frame.frame_id,
                  frame.qos_class,
                  info={"tenant": frame.tenant})

    def qos_stats(self) -> dict:
        """The QoS plane's live view: per-tenant budgets/in-flight/
        shed counters plus the promotion total (None-safe)."""
        if self.qos is None:
            return {"enabled": False}
        stats = self.qos.stats()
        stats["enabled"] = True
        stats["promotions_recorded"] = self._qos_promotions
        stats["sheds_recorded"] = self._qos_sheds
        return stats

    def note_slo_burn(self, fired=None, burns=None) -> None:
        """SLO burn telemetry handed over from the gateway's result
        pump (event-loop method via ``post_self``: share, ring and
        black-box are not pump-thread-safe).  ``burns`` refreshes the
        ``slo_burn`` share key; each ``fired`` entry is a fast burn --
        ring event plus debounced black-box dump, because the error
        budget is burning NOW and the ring tail holds the frames that
        burned it."""
        if burns is not None:
            self.share["slo_burn"] = {
                str(tenant): {str(cls): entry.get("burn")
                              for cls, entry in classes.items()}
                for tenant, classes in burns.items()}
        for entry in fired or ():
            tenant, qos_class, burn = entry[0], entry[1], entry[2]
            self._rec("slo_burn", None, None, str(tenant), None,
                      {"cls": str(qos_class),
                       "burn": round(float(burn), 3)})
            self._blackbox(
                "slo_burn",
                detail=f"tenant {tenant} class {qos_class} "
                       f"burn {float(burn):.2f}x")
        if fired and self.controller is not None:
            # The controller's spawn tier keys urgency off fast burns
            # (burn_rates alone lags by the SLO window).
            self.controller.note_burns(fired)

    def _stamp_deadline(self, stream: Stream, frame: Frame) -> None:
        if not stream.deadline_ms:
            return
        frame.deadline = time.monotonic() + stream.deadline_ms / 1000.0
        self.post_self("expire_frame",
                       [stream.stream_id, frame.frame_id, frame],
                       delay=stream.deadline_ms / 1000.0 + 0.002)

    def _past_deadline(self, frame: Frame) -> bool:
        if self._draining:
            # A drain window suspends SLO enforcement: a deadline
            # error is a DELIVERY, and everything delivered here
            # would be excluded from the adopter's replay -- the
            # zero-drop handoff beats a late-frame error.
            return False
        return frame.deadline is not None \
            and time.monotonic() > frame.deadline

    # -- remote-stage circuit breaker --------------------------------------

    def _stage_breaker(self, node_name: str) -> CircuitBreaker | None:
        """The per-remote-stage breaker (None when disabled via
        ``breaker_threshold: 0``)."""
        threshold = int(parse_number(
            self.get_pipeline_parameter("breaker_threshold"),
            BREAKER_THRESHOLD_DEFAULT))
        if threshold <= 0:
            return None
        breaker = self.breakers.get(node_name)
        if breaker is None:
            cooldown = float(parse_number(
                self.get_pipeline_parameter("breaker_cooldown_ms"),
                BREAKER_COOLDOWN_MS_DEFAULT)) / 1000.0
            breaker = self.breakers[node_name] = CircuitBreaker(
                threshold, cooldown)
        return breaker

    def _run_fallback(self, stream: Stream, frame: Frame, node):
        """Run a remote stage's declared ``fallback:`` element locally
        while the breaker is open (degraded mode).  Outputs map out
        under the REMOTE node's name so downstream mappings hold.
        Returns True (ran, keep walking), False (no fallback declared),
        None (frame errored)."""
        definition = node.element.definition
        fallback_name = definition.fallback if definition else None
        if not fallback_name:
            return False
        element = self._fallback_elements.get(node.name)
        if element is None:
            element_def = self.definition.element(fallback_name)
            cls = self._load_element_class(element_def.deploy_local,
                                           fallback_name)
            context = ElementContext(fallback_name, element_def, self,
                                     dict(element_def.parameters))
            element = self._fallback_elements[node.name] = cls(context)
        inputs, missing, _ = self._map_in_for(element,
                                              node.properties or {},
                                              frame.swag, frame=frame,
                                              stream=stream)
        if missing:
            self._frame_error(stream, frame,
                              f"{fallback_name} (fallback for "
                              f"{node.name}): missing inputs {missing}")
            return None
        try:
            result = element.process_frame(stream, **inputs)
        except Exception as error:
            self.logger.exception("fallback %s raised", fallback_name)
            self._frame_error(stream, frame,
                              f"{fallback_name} (fallback for "
                              f"{node.name}): {error}")
            return None
        event, outputs = result if isinstance(result, tuple) \
            else (result, {})
        if event != StreamEvent.OKAY:
            diagnostic = (outputs or {}).get("diagnostic", "") \
                if isinstance(outputs, dict) else ""
            self._frame_error(stream, frame,
                              f"{fallback_name} (fallback for "
                              f"{node.name}): {diagnostic or event}")
            return None
        self._map_out(node, frame, outputs or {})
        frame.metrics["breaker_fallbacks"] = \
            frame.metrics.get("breaker_fallbacks", 0) + 1
        if self.telemetry is not None:
            self.telemetry.registry.count("breaker_fallbacks",
                                          stage=node.name)
        self.logger.warning("stream %s frame %s: breaker open, ran "
                            "fallback %s for %s", stream.stream_id,
                            frame.frame_id, fallback_name, node.name)
        return True

    def metrics_text(self) -> str:
        """Prometheus-style text exposition of the telemetry plane
        (histogram quantiles, counters, engine gauges).  Empty when
        ``telemetry: off``.  Safe to call from any thread -- this is
        what the ``--metrics-port`` HTTP endpoint serves."""
        if self.telemetry is None:
            return ""
        return self.telemetry.metrics_text()

    def get_trace(self, trace_id: str) -> dict | None:
        """One reconstructed trace (all spans, both processes for
        remote hops) from the TraceBuffer, or None."""
        if self.telemetry is None:
            return None
        return self.telemetry.traces.get(str(trace_id))

    # -- flight recorder + critical path (ISSUE 10) ------------------------

    def _rec(self, etype: str, stream=None, frame=None, name=None,
             ms=None, info=None) -> None:
        """One guarded flight-recorder append (no-op under
        ``recorder: off``).  Sites may only pass ids/names/numbers --
        the black-box dump's redaction rests on it."""
        recorder = self.recorder
        if recorder is not None:
            recorder.record(etype, stream, frame, name, ms, info)

    def explain(self, top_k: int = 5) -> dict:
        """Aggregate critical-path report over the trace buffer: bucket
        totals (compute / queue / hop / fetch / pipe / replay /
        pacing), per-stage/replica splits, and the top-k (stage,
        bucket) contributors -- the "where did the time go" answer for
        recent traffic.  Thread-safe (trace buffer snapshots under its
        lock); empty when ``telemetry: off``."""
        if self.telemetry is None:
            return {}
        report = aggregate_traces(self.telemetry.traces.snapshot(),
                                  top_k=top_k)
        report["pipeline"] = self.name
        if self.recorder is not None:
            report["recorder"] = self.recorder.stats
        return report

    def explain_frame(self, frame_id, stream_id=None) -> dict | None:
        """One frame's causal story: the flight-recorder timeline (what
        happened, in order, with every interval attributed to a
        bucket) plus its trace spans and completion attribution.  Works
        for in-flight frames too (partial timeline); None when neither
        the ring nor the trace buffer knows the frame.  Thread-safe.

        Frame ids restart per stream (and per stream INCARNATION):
        with ``stream_id`` omitted the NEWEST stream holding that
        frame id wins, and within a stream only the newest incarnation
        segment is used (``FlightRecorder.frame_events``) -- never a
        merge of same-id frames, which would attribute one frame's
        waits to another's compute and terminate the timeline at the
        wrong ``done``."""
        trace = None
        if isinstance(frame_id, str):
            # A gateway-minted trace id names the request end to end:
            # resolve it to the frame/stream its spans carry, then
            # explain that frame as usual (one id, door to decode).
            # Trace-id lookup first: an (unlikely) all-digit trace id
            # must not silently degrade to a frame-id lookup.
            if self.telemetry is not None:
                trace = self.telemetry.traces.get(frame_id)
            if trace is None:
                if frame_id.lstrip("-").isdigit():
                    frame_id = int(frame_id)
                else:
                    return None
        if trace is not None:
            frame_id, span_stream = None, None
            for span in trace.get("spans", []):
                if span.get("frame") is not None:
                    frame_id = span["frame"]
                    span_stream = span.get("stream") or span_stream
            if frame_id is None:
                return None
            if stream_id is None:
                stream_id = span_stream
        events = []
        if self.recorder is not None:
            if stream_id is None:
                candidates = self.recorder.snapshot(frame=frame_id)
                if candidates:
                    stream_id = candidates[-1][2]
            if stream_id is not None:
                events = self.recorder.frame_events(stream_id,
                                                    frame_id)
        if trace is None:
            trace = None if self.telemetry is None else \
                self.telemetry.traces.by_frame(frame_id,
                                               stream=stream_id)
        if not events and trace is None:
            return None
        result: dict = {"frame": int(frame_id),
                        "stream": None if stream_id is None
                        else str(stream_id)}
        if events:
            result.update(attribute_events(events))
        if trace is not None:
            result["trace_id"] = trace["trace_id"]
            result["okay"] = trace["okay"]
            result["spans"] = trace["spans"]
            if not events:
                # Ring already wrapped past this frame: fall back to
                # the completion-time attribution on the trace entry.
                for key in ("buckets", "stages", "e2e_ms",
                            "unattributed_ms", "coverage"):
                    if trace.get(key) is not None:
                        result[key] = trace[key]
        return result

    def _frame_states(self) -> list[dict]:
        """Redacted in-flight frame states for the black-box dump:
        position + numeric metrics + swag KEY names -- never values."""
        states = []
        for stream in self.streams.values():
            for frame in stream.frames.values():
                states.append({
                    "stream": stream.stream_id,
                    "frame": frame.frame_id,
                    "paused": frame.paused_pe_name,
                    "stage": frame.stage,
                    "replica": frame.stage_replica,
                    "waiting": frame.stage_waiting,
                    "replays": frame.replays,
                    "age_s": round(time.monotonic() - frame.created, 3),
                    "swag_keys": sorted(str(key) for key in frame.swag),
                    "metrics": {key: value for key, value
                                in frame.metrics.items()
                                if isinstance(value,
                                              (int, float, bool, str))}})
        return states

    def _blackbox(self, reason: str, stream=None, frame=None,
                  detail: str = "") -> None:
        """Snapshot the flight-recorder tail + in-flight frame states
        to a bounded JSON dump under ``blackbox_dir`` (off when the
        parameter is unset or the recorder is off).  Runs on the event
        loop at failure-transition sites, debounced per reason
        (``_BLACKBOX_COOLDOWN_S``): a sustained episode -- every frame
        of an overloaded stream missing its deadline -- must cost ONE
        dump per window, not a serialize+glob on the latency-critical
        loop per failure (the first dump's ring tail already holds the
        episode; later near-identical snapshots would only evict it)."""
        directory = self._blackbox_dir
        if directory is None or self.recorder is None:
            return
        now = time.monotonic()
        last = self._blackbox_last.get(reason)
        if last is not None and now - last < _BLACKBOX_COOLDOWN_S:
            return
        try:
            payload = {"reason": reason,
                       "pipeline": self.name,
                       "wall_time": time.time(),
                       "stream": None if stream is None else str(stream),
                       "frame": frame,
                       "detail": str(detail)[:500],
                       "generation": self.stage_placement.generation
                       if self.stage_placement is not None else 0,
                       "recorder": self.recorder.stats,
                       "frames": self._frame_states(),
                       "events": events_as_dicts(
                           self.recorder.snapshot(tail=1024))}
            path = write_blackbox(directory, payload,
                                  limit=self._blackbox_limit)
            # Charge the cooldown only on a SUCCESSFUL write: a full
            # disk must not silently eat the whole episode's window.
            self._blackbox_last[reason] = now
            self._blackbox_dumps += 1
            self.share["blackbox_dumps"] = self._blackbox_dumps
            self.logger.warning("black-box dump (%s): %s", reason, path)
        except Exception:
            self.logger.exception("black-box dump failed (%s)", reason)

    def _breaker_failure(self, name: str, breaker,
                         stream=None, frame=None) -> None:
        """Charge a remote stage's breaker, recording the transition --
        an OPEN transition is a black-box trigger (the stage just went
        dark; the ring tail holds the round trips that killed it)."""
        was = breaker.state
        breaker.record_failure()
        now = breaker.state
        if now != was:
            self._rec("breaker", stream, frame, name,
                      info={"state": now})
            if now == "open":
                self._blackbox("breaker_open", stream, frame,
                               detail=f"stage {name}")

    def _breaker_success(self, name: str, breaker,
                         stream=None, frame=None) -> None:
        was = breaker.state
        breaker.record_success()
        if breaker.state != was:
            self._rec("breaker", stream, frame, name,
                      info={"state": breaker.state})

    # -- stream lifecycle --------------------------------------------------

    def create_stream(self, stream_id=None, *parameters):
        """Wire command: ``(create_stream id (params...) grace_time)``.
        A ``graph_path`` entry in the params dict selects which named
        graph path (head element) this stream runs (reference
        pipeline.py:641 create_stream(graph_path=...); example:
        examples/pipeline/pipeline_paths.json)."""
        params = dict(parameters[0]) if parameters and isinstance(
            parameters[0], dict) else {}
        grace_time = parse_number(parameters[1], _GRACE_TIME_DEFAULT) \
            if len(parameters) > 1 else _GRACE_TIME_DEFAULT
        graph_path = params.pop("graph_path", None)
        self.create_stream_local(stream_id or DEFAULT_STREAM_ID,
                                 parameters=params, graph_path=graph_path,
                                 grace_time=grace_time)

    def create_stream_local(self, stream_id, parameters=None,
                            graph_path=None, grace_time=_GRACE_TIME_DEFAULT,
                            queue_response=None, topic_response=None) \
            -> Stream | None:
        stream_id = str(stream_id)
        if stream_id in self.streams:
            self.logger.warning("stream %s already exists", stream_id)
            return self.streams[stream_id]
        heads = [node.name for node in self.graph.heads]
        if graph_path is not None and str(graph_path) not in heads:
            # Heads only: starting mid-graph would skip the head
            # element's outputs and run a partial path.
            self.logger.error("stream %s: graph_path %r is not a graph "
                              "head (heads: %s)", stream_id, graph_path,
                              heads)
            return None
        stream = Stream(stream_id=stream_id, graph_path=graph_path,
                        parameters=dict(parameters or {}),
                        queue_response=queue_response,
                        topic_response=topic_response)
        stream.device_inflight = int(parse_number(
            stream.parameters.get(
                "device_inflight",
                self._pipeline_parameters.get("device_inflight")),
            DEVICE_INFLIGHT_DEFAULT))
        fuse = str(stream.parameters.get(
            "fuse", self._pipeline_parameters.get("fuse", "auto"))) \
            .strip().lower()
        if fuse not in FUSE_MODES:
            self.logger.warning("stream %s: fuse=%r not one of %s; "
                                "using auto", stream_id, fuse, FUSE_MODES)
            fuse = "auto"
        stream.fuse = fuse
        # Per-frame deadline + overload shedding (ISSUE 5), resolved
        # once per stream: stream parameters win over pipeline
        # parameters, like device_inflight above.
        stream.deadline_ms = float(parse_number(
            stream.parameters.get(
                "frame_deadline_ms",
                self._pipeline_parameters.get("frame_deadline_ms")),
            0.0))
        policy = str(stream.parameters.get(
            "overload_policy",
            self._pipeline_parameters.get("overload_policy",
                                          "block"))).strip().lower()
        if policy not in OVERLOAD_POLICIES:
            self.logger.warning("stream %s: overload_policy=%r not one "
                                "of %s; using block", stream_id, policy,
                                OVERLOAD_POLICIES)
            policy = "block"
        stream.overload_policy = policy
        stream.overload_limit = int(parse_number(
            stream.parameters.get(
                "overload_limit",
                self._pipeline_parameters.get("overload_limit")),
            OVERLOAD_LIMIT_DEFAULT))
        # Unified QoS admission (ISSUE 12): tenant identity + priority
        # class resolve once per stream (gateway sessions set them;
        # anything else lands on the default tenant's class).  An
        # unknown class falls back rather than erroring -- the gateway
        # validates client input at ITS boundary; a local caller's
        # typo must not kill the stream.
        stream.tenant = str(stream.parameters.get("tenant", "default"))
        requested_class = stream.parameters.get("qos_class")
        if self.qos is not None:
            resolved = self.qos.resolve_class(requested_class,
                                              stream.tenant)
            if requested_class is not None \
                    and str(requested_class) != resolved:
                self.logger.warning(
                    "stream %s: qos_class=%r unknown; using %s",
                    stream_id, requested_class, resolved)
            stream.qos_class = resolved
        elif requested_class is not None:
            stream.qos_class = str(requested_class)
        # Durable journal (ISSUE 13): resolved once per stream; a
        # stream-level ``journal: off`` opts out (one-shot HTTP
        # streams, sub-streams nothing will ever adopt).
        if self.journal is not None:
            stream.journal = str(stream.parameters.get(
                "journal", "on")).strip().lower() \
                not in ("off", "false", "0")
        if self.journal is not None and stream.journal:
            self.journal.stream_open(stream_id, stream.parameters,
                                     graph_path=graph_path,
                                     topic_response=topic_response)
        if grace_time:
            stream.lease = Lease(
                self.runtime.engine, float(grace_time), stream_id,
                expired_handler=self._stream_lease_expired)
        self.streams[stream_id] = stream
        self.ec_producer.update("streams", len(self.streams))

        self._current_stream_ref = stream
        try:
            for node in self._stream_path(stream):
                element = node.element
                if isinstance(element, RemoteStage):
                    self._forward_stream_op(element, "create_stream",
                                            stream, grace_time)
                    continue
                element.compile_element(stream)
                event, diagnostic = element.start_stream(stream, stream_id) \
                    or (StreamEvent.OKAY, {})
                if event == StreamEvent.ERROR:
                    self.logger.error("start_stream %s failed: %s",
                                      node.name, diagnostic)
                    self._destroy_stream_now(stream_id)
                    return None
        finally:
            self._current_stream_ref = None
        stream.state = StreamState.RUN
        return stream

    def _stream_lease_expired(self, lease):
        """A stream's grace lease reaps IDLE streams only.  The
        reference extends its stream lease on every processed frame
        (reference main/pipeline.py:1425 ``stream_lease.extend()``);
        here frames can sit PARKED at async/remote stages for minutes
        with no per-frame tick (a first-frame jit compile of a 1B model
        takes >120 s through a congested link), so the expiry itself
        re-checks: frames in flight, or activity within the last grace
        period, revives the lease instead of destroying mid-work.  A
        frame parked longer than ``_STALL_REAP_FACTOR`` grace periods
        no longer counts as alive -- a remote stage that died without
        replying, or an async element that never calls complete(),
        must not pin the stream (and its swag tensors) forever."""
        stream = self.streams.get(str(lease.lease_uuid))
        if stream is not None:
            now = time.monotonic()
            stall_cap = lease.lease_time * _STALL_REAP_FACTOR
            live_frames = any(now - frame.created < stall_cap
                              for frame in stream.frames.values())
            if live_frames or now - stream.last_frame_time \
                    < lease.lease_time:
                lease.revive()
                return
            if stream.frames:
                self.logger.error(
                    "stream %s: reaping with %d frame(s) parked beyond "
                    "%.0f s (stage never completed)", stream.stream_id,
                    len(stream.frames), stall_cap)
        self.destroy_stream(lease.lease_uuid)

    def _stream_path(self, stream: Stream):
        return self.graph.get_path(stream.graph_path)

    def _forward_stream_op(self, stage: RemoteStage, op: str,
                           stream: Stream, *args):
        if stage.remote_topic_path is None:
            return
        proxy = get_service_proxy(self.runtime, stage.remote_topic_path)
        getattr(proxy, op)(stream.stream_id, *args)

    def destroy_stream(self, stream_id=None, graceful=False):
        graceful = graceful in (True, "True", "true", "1")
        stream_id = str(stream_id or DEFAULT_STREAM_ID)
        stream = self.streams.get(stream_id)
        if stream is None:
            return
        if graceful and stream.in_flight:
            # retry shortly; frames still pending
            self.post_self("destroy_stream", [stream_id, True], delay=0.1)
            return
        self._destroy_stream_now(stream_id)

    def _destroy_stream_now(self, stream_id: str):
        stream = self.streams.pop(stream_id, None)
        if stream is None:
            return
        if stream.state != StreamState.ERROR:
            stream.state = StreamState.STOP
        if stream.lease is not None:
            stream.lease.terminate()
        stream.device_window.clear()    # drop refs without blocking
        # Stage credits held by this stream's in-flight frames go back
        # to the window (and wake other streams' queued frames); queued
        # tokens for dead frames are skipped lazily when popped.
        for frame in list(stream.frames.values()):
            self._qos_done(frame)
            self._release_stage(stream, frame)
        # Completed frames' responses still buffered behind an
        # in-flight predecessor: deliver them (best-effort seq order)
        # rather than dropping finished work -- pre-reorder-buffer
        # behavior responded at completion, and callers count replies.
        for seq in sorted(stream.delivery_pending):
            item = stream.delivery_pending.pop(seq)
            if item is not None:
                done_frame, okay, diagnostic = item
                self._respond(stream, done_frame, okay, diagnostic)
        # Fused segments are stream-owned (their captures/parameters
        # resolved against this stream): release them with it, or the
        # registry pins stale compiled calls (and captured weights)
        # forever under churning streams.
        self.fused_segments = [segment for segment in self.fused_segments
                               if segment.stream_id != stream_id]
        self.share["swag_host_transfers"] = self.transfer_ledger.implicit
        self._current_stream_ref = stream
        try:
            for node in self._stream_path(stream):
                element = node.element
                try:
                    if isinstance(element, RemoteStage):
                        self._forward_stream_op(element, "destroy_stream",
                                                stream)
                    else:
                        element.stop_stream(stream, stream_id)
                except Exception:
                    self.logger.exception("stop_stream %s failed", node.name)
        finally:
            self._current_stream_ref = None
        if self.telemetry is not None:
            # After the release loop above: the spans it buffered for
            # this dead incarnation must not leak onto a recreated
            # same-id stream's frames (ids restart per stream).
            self.telemetry.stream_destroyed(stream_id)
        # Incarnation boundary on the flight-recorder ring: a recreated
        # same-id stream's frame timelines must not merge with this
        # dead incarnation's same-id frames (recorder.frame_events
        # splits at this marker -- the ring itself is append-only).
        self._rec("stream_end", stream_id)
        if self.journal is not None and stream.journal \
                and not self._draining:
            # Graceful destroy leaves nothing to adopt.  A DRAINING
            # pipeline's streams stay OPEN in the journal: their
            # undelivered frames are the handoff.
            self.journal.stream_close(stream_id)
        self.ec_producer.update("streams", len(self.streams))

    # -- process-level fault domain (ISSUE 13) -----------------------------

    def kill(self):
        """Simulate unclean process death for THIS pipeline service
        (the in-process twin of SIGKILL, for chaos tests and the
        ``process_kill`` fault point): publish the retained
        ``(absent)`` the per-service LWT would have sent (the
        registrar reaps the service, peers' discovery fires), stop
        serving every topic and mailbox, and drop all streams with NO
        responses.  The journal is left exactly as the crash left it
        -- that is the artifact a peer adopts."""
        if getattr(self, "_killed", False):
            return
        self._killed = True
        self.logger.warning("pipeline %s: unclean death (kill)",
                            self.name)
        try:
            self.publish_state("(absent)")
        except Exception:
            pass
        engine = self.runtime.engine
        engine.remove_mailbox_handler(self._mailbox_control)
        engine.remove_mailbox_handler(self._mailbox_in)
        self.runtime.remove_message_handler(self._topic_control_handler,
                                            self.topic_control)
        self.runtime.remove_message_handler(self._topic_in_handler,
                                            self.topic_in)
        self._cancel_health_timer()     # autoscale timer included
        if self.gateway is not None:
            self.gateway.stop()
            self.gateway = None
        if self._data_endpoint is not None:
            self._data_endpoint.close()
            self._data_endpoint = None
        for stream in list(self.streams.values()):
            if stream.lease is not None:
                stream.lease.terminate()
            for handle in stream.generator_handles:
                handle.set()
            stream.device_window.clear()
        self.streams.clear()
        if self.stage_scheduler is not None:
            self.stage_scheduler.stop()

    def adopt(self, source=None, response_topic=None,
              adopt_limit=None):
        """Wire/local command: ``(adopt <pipeline-or-journal-path>
        [response_topic])`` -- reconstruct a dead peer's live streams
        from its journal and replay every undelivered frame, in
        order, deduped by the delivered-set (nothing the peer already
        answered is re-sent).  LLM streams resume at their journaled
        committed token prefix.  Exactly one adopter wins the
        journal's claim file; a stream id that already exists locally
        is refused individually.  Bounded by ``adopt_limit`` the way
        replay is by ``replay_limit``.  Returns the number of streams
        adopted."""
        if self.journal is None and not self._journal_dir:
            self.logger.error("adopt: no journal_dir configured")
            return 0
        if self._draining:
            self.logger.warning("adopt: refusing while draining")
            return 0
        source = str(source or "")
        if source.endswith(".journal") or os.sep in source:
            path = source
        else:
            path = os.path.join(self._journal_dir,
                                f"{source}.journal")
        name = os.path.basename(path).rsplit(".journal", 1)[0]
        if self.journal is not None \
                and os.path.abspath(path) == \
                os.path.abspath(self.journal.path):
            self.logger.error("adopt: refusing to adopt my own journal")
            return 0
        if not os.path.exists(path):
            self.logger.warning("adopt: journal %s does not exist",
                                path)
            return 0
        # Read BEFORE claiming: a journal with nothing live to adopt
        # (typically the dead pipeline's supervisor respawned it
        # first, truncating to a fresh incarnation and orphaning the
        # crash state) must not be claimed -- a stale claim on a LIVE
        # pipeline's journal would fence its NEXT death's adoption.
        state = load_journal(path)
        if not state.live_streams():
            self.logger.warning(
                "adopt: journal %s has no live streams (respawned "
                "fresh, drained clean, or empty); nothing to adopt",
                path)
            return 0
        if not claim_adoption(path, self.name):
            # Double adoption would double-replay undelivered frames.
            self.logger.warning(
                "adopt: journal %s already claimed; refusing", path)
            return 0
        state = load_journal(path)
        limit = int(parse_number(adopt_limit, self._adopt_limit))
        adopted = replayed = skipped = 0
        for entry in state.live_streams():
            if entry.stream_id in self.streams:
                self.logger.warning(
                    "adopt: stream %s already live here; refusing it",
                    entry.stream_id)
                continue
            if adopted >= limit:
                skipped += 1
                continue
            # The stream's OWN journaled response topic wins: a direct
            # wire client's replayed results must go back to it, not
            # to the gateway that happened to command the adoption
            # (whose topic is the fallback for queue-based sessions
            # that had no topic to journal).
            topic = entry.topic_response or response_topic
            stream = self.create_stream_local(
                entry.stream_id, parameters=dict(entry.parameters),
                graph_path=entry.graph_path, topic_response=topic)
            if stream is None:
                continue
            adopted += 1
            stream.frame_count = max(
                entry.done_upto + 1,
                (max(entry.frames) + 1) if entry.frames else 0)
            undelivered = entry.undelivered
            self._rec("adopt", entry.stream_id, None, name,
                      info={"frames": len(undelivered)})
            for frame_id, tokens in sorted(entry.llm.items()):
                if not tokens:
                    continue
                self._journal_resume[(entry.stream_id,
                                      int(frame_id))] = list(tokens)
                if self.journal is not None and stream.journal:
                    # The inherited prefix becomes durable HERE, so a
                    # second failover resumes from the same place.
                    self.journal.llm_tokens(entry.stream_id, frame_id,
                                            tokens)
            for frame_id in undelivered:
                record = entry.frames[frame_id]
                try:
                    data = decode_payload(record.get("data"))
                except Exception as error:
                    self.logger.warning(
                        "adopt: stream %s frame %s payload "
                        "undecodable (%s); dropped", entry.stream_id,
                        frame_id, error)
                    continue
                replayed += 1
                # The journaled trace_id rides the replay: the frame's
                # spans on THIS pipeline continue the original door-to-
                # decode trace across the process kill.
                self._ingest({"stream_id": entry.stream_id,
                              "frame_id": frame_id,
                              "response_topic": topic,
                              "trace_id": record.get("tid")}, data)
        self._streams_adopted += adopted
        self._frames_journal_replayed += replayed
        self.share["streams_adopted"] = self._streams_adopted
        self.share["frames_journal_replayed"] = \
            self._frames_journal_replayed
        if self.telemetry is not None and adopted:
            self.telemetry.registry.count("streams_adopted", adopted)
            self.telemetry.registry.count("frames_journal_replayed",
                                          replayed)
        self.logger.info(
            "adopted %d stream(s) / %d frame(s) from %s%s", adopted,
            replayed, name,
            f" ({skipped} past adopt_limit)" if skipped else "")
        return adopted

    def drain(self, *_args):
        """Wire/CLI command: cooperative shutdown with zero frame
        drop.  Admission stops (frames arriving from now on are
        journaled and PARKED for the adopter, never run), in-flight
        LLM requests are migrated at their committed prefix (their
        tokens are already journaled; the element cancels them and
        drops the parked frames without responding), in-flight plain
        frames get ``drain_timeout_ms`` to finish normally, then the
        journal is marked cleanly drained and the service announces
        its death -- the same LWT path an unclean kill takes, so the
        gateway's failover machinery hands the sessions to a peer
        that adopts the journal.  Rolling restarts are this, per
        pipeline, in sequence."""
        if self._draining:
            return
        self._draining = True
        self._rec("drain", None, info={"phase": "start"})
        self.logger.info("pipeline %s: draining (timeout %.0f ms)",
                         self.name, self._drain_timeout_ms)
        for node in self.graph.nodes():
            drainer = getattr(node.element, "drain_requests", None)
            if callable(drainer):
                try:
                    drainer()
                except Exception:
                    self.logger.exception("drain_requests failed for "
                                          "%s", node.name)
        self._drain_deadline = time.monotonic() \
            + self._drain_timeout_ms / 1000.0
        self.post_self("drain_tick", [])

    def drain_tick(self):
        """Drain progress check (self-posted): in-flight frames get
        until the deadline; whatever is still parked then is handed
        to the adopter through the journal."""
        if not self._draining or self._drained:
            return
        busy = sum(len(stream.frames)
                   for stream in self.streams.values())
        if busy and time.monotonic() < self._drain_deadline:
            self.post_self("drain_tick", [], delay=0.02)
            return
        self._drain_finish(busy)

    def _drain_finish(self, leftover: int) -> None:
        for stream in list(self.streams.values()):
            for frame in list(stream.frames.values()):
                # Parked past the deadline: parked for adoption.  No
                # response -- the adopter's replay is the response.
                stream.frames.pop(frame.frame_id, None)
                self._qos_done(frame)
                self._release_stage(stream, frame)
        if self.journal is not None:
            self.journal.mark_drained()
        self._drained = True
        self._rec("drain", None, info={"phase": "done",
                                       "leftover": leftover})
        self.logger.info("pipeline %s: drained (%d frame(s) parked "
                         "for adoption)", self.name, leftover)
        try:
            self.publish_state("(absent)")
        except Exception:
            pass
        # Retirement GRACE, not immediate stop: until the gateway's
        # settle window elapses and its sessions re-bind, frames
        # already in flight toward this pipeline keep arriving -- each
        # must still ingest (journal + hold, the ``_draining`` path)
        # so the adopter's journal read includes it.  Retiring the
        # mailbox inside that window would drop exactly the frames the
        # zero-drop contract promises to keep.
        self.runtime.engine.add_oneshot_timer(self._retire_after_drain,
                                              _DRAIN_RETIRE_GRACE_S)

    def _retire_after_drain(self):
        # The share marker is the process-exit signal (``pipeline
        # create`` runs until it): set AFTER the grace, so a
        # supervisor cannot reap the process while stragglers are
        # still being journaled.
        self.share["drained"] = True
        try:
            self.ec_producer.update("drained", True)
        except Exception:
            pass
        try:
            self.stop()
        except Exception:
            self.logger.exception("post-drain stop failed")

    def take_journal_resume(self, stream_id, frame_id) -> list | None:
        """Adopted LLM committed prefix for (stream, frame), consumed
        exactly once by the serving element."""
        return self._journal_resume.pop(
            (str(stream_id), int(frame_id)), None)

    def current_frame(self) -> Frame | None:
        """The frame whose element dispatch is running on the event
        loop right now (async submit seam) -- lets an element key
        per-frame engine state (journal resume) without a signature
        change."""
        return self._current_frame_ref

    def failover_stats(self) -> dict:
        return {
            "journal": None if self.journal is None
            else self.journal.stats(),
            "draining": self._draining, "drained": self._drained,
            "streams_adopted": self._streams_adopted,
            "frames_journal_replayed": self._frames_journal_replayed,
            "resume_pending": len(self._journal_resume)}

    # -- frame ingestion ---------------------------------------------------

    def process_frame(self, stream_dict=None, frame_data=None):
        """Wire command: ``(process_frame (stream_id: X ...) (k: v ...))``.
        Values arrive as strings/encoded blobs; decode and run.  A
        ``pipe_token`` header means the frame's tensors rode the
        binary data plane: claim them from the endpoint (deferring the
        envelope when they are still in TCP flight) and merge them in
        -- zero base64, zero host copy beyond the socket read."""
        stream_dict = dict(stream_dict or {})
        frame_data = dict(frame_data or {})
        claimed = self._claim_for_ingest(stream_dict, frame_data)
        if claimed is None:
            return              # deferred / held / dropped
        frame_data = decode_frame_data(frame_data)
        if claimed:
            frame_data.update(self._upload_claimed(
                stream_dict.get("stream_id", DEFAULT_STREAM_ID),
                claimed))
        self._ingest(stream_dict, frame_data)

    def process_frame_local(self, frame_data: dict,
                            stream_id=DEFAULT_STREAM_ID,
                            queue_response=None,
                            frame_id=None, trace_id=None,
                            trace_parent=None) -> None:
        """In-process API: no encoding, swag values pass by reference.
        Thread-safe (hops through the actor mailbox).  An explicit
        ``frame_id`` lets a session-owning caller (the gateway) keep
        one frame-id space across pipeline failovers, so delivery
        dedupe works no matter which peer answers.  ``trace_id`` /
        ``trace_parent`` let a door-owning caller (the gateway) root
        this frame's spans under ITS trace instead of minting a new
        one -- the in-process twin of the wire header's trace fields."""
        self.post_self("ingest_local",
                       [str(stream_id), frame_data, queue_response,
                        frame_id, trace_id, trace_parent])

    def ingest_local(self, stream_id, frame_data, queue_response=None,
                     frame_id=None, trace_id=None, trace_parent=None):
        stream = self.streams.get(str(stream_id))
        if stream is None:
            stream = self.create_stream_local(stream_id,
                                              queue_response=queue_response)
            if stream is None:
                return
        elif queue_response is not None:
            stream.queue_response = queue_response
        if frame_id is None:
            frame_id = stream.next_frame_id()
        else:
            frame_id = int(frame_id)
            stream.frame_count = max(stream.frame_count, frame_id + 1)
        frame = Frame(frame_id=frame_id, swag=dict(frame_data))
        if self.telemetry is not None:
            self.telemetry.frame_started(frame, trace_id=trace_id,
                                         parent_id=trace_parent)
        self._rec("ingest", stream.stream_id, frame.frame_id)
        self._stamp_qos(stream, frame)
        shed = self._shed_for_overload(stream) \
            or self._qos_shed_for_overload(stream, frame)
        self._assign_delivery_seq(stream, frame)
        stream.frames[frame.frame_id] = frame
        self._journal_ingest(stream, frame)
        if self._draining:
            self._hold_for_drain(stream, frame)
            return
        if self._faults is not None \
                and self._process_fault_probe(stream, frame):
            return
        if shed:
            self._shed_incoming(stream, frame)
            return
        self._stamp_deadline(stream, frame)
        # Bounded dispatch window: before this frame's device work
        # enqueues, sync the oldest completed-but-unsynced frame(s) so
        # dispatch stays at most device_inflight frames ahead
        # (per-class caps apply -- QoS plane 1).
        paced = stream.device_window.pace(self._device_limit(stream))
        if paced:
            self._note_pace(stream, frame, paced)
        self._process_frame_common(stream, frame)

    def _ingest(self, stream_dict: dict, frame_data: dict):
        stream_id = str(stream_dict.get("stream_id", DEFAULT_STREAM_ID))
        stream = self.streams.get(stream_id)
        if stream is None:
            stream = self.create_stream_local(stream_id)
            if stream is None:
                return
        frame_id = parse_number(stream_dict.get("frame_id"), None)
        if frame_id is None:
            frame_id = stream.next_frame_id()
        frame = Frame(frame_id=int(frame_id), swag=dict(frame_data))
        frame.response_topic = stream_dict.get("response_topic")
        # The origin's tensor-pipe endpoint, when it advertises one:
        # this process ships the response's tensors back over it.
        frame.pipe_reply = stream_dict.get("pipe_reply")
        if self.telemetry is not None:
            # A forwarded frame carries its origin's trace context: the
            # spans stamped here join THAT trace (and ride back in the
            # response) instead of starting a new one.
            self.telemetry.frame_started(
                frame, trace_id=stream_dict.get("trace_id"),
                parent_id=stream_dict.get("trace_parent"))
        stale = stream.frames.get(frame.frame_id)
        if stale is not None:
            # A wire caller re-ingested a live frame id: the replaced
            # frame's delivery slot (and stage credit) must not wedge
            # the stream's reorder buffer / admission window.
            self._qos_done(stale)
            self._release_stage(stream, stale)
            self._deliver(stream, stale, okay=False, skip=True)
        self._rec("ingest", stream.stream_id, frame.frame_id)
        self._stamp_qos(stream, frame)
        shed = self._shed_for_overload(stream) \
            or self._qos_shed_for_overload(stream, frame)
        self._assign_delivery_seq(stream, frame)
        stream.frames[frame.frame_id] = frame
        self._journal_ingest(stream, frame)
        if self._draining:
            self._hold_for_drain(stream, frame)
            return
        if self._faults is not None \
                and self._process_fault_probe(stream, frame):
            return
        if shed:
            self._shed_incoming(stream, frame)
            return
        self._stamp_deadline(stream, frame)
        paced = stream.device_window.pace(self._device_limit(stream))
        if paced:
            self._note_pace(stream, frame, paced)
        self._process_frame_common(stream, frame)

    # -- process fault domain (ISSUE 13) -----------------------------------

    def _journal_ingest(self, stream: Stream, frame: Frame) -> None:
        """Journal commit point: the frame's host-visible inputs, so a
        peer can replay it if this process dies before delivery."""
        if self.journal is None or not stream.journal:
            return
        lag = self.journal.frame_ingested(stream.stream_id,
                                          frame.frame_id, frame.swag,
                                          trace_id=frame.trace_id)
        if lag >= 256:
            # The fsync backlog grew a whole batch window deep --
            # frames in it are past the durability horizon if the host
            # (not just the process) dies.  Ring-logged, throttled.
            now = time.monotonic()
            if now - self._journal_lag_noted > 1.0:
                self._journal_lag_noted = now
                self._rec("journal_lag", stream.stream_id,
                          frame.frame_id, info={"pending": lag})

    def _hold_for_drain(self, stream: Stream, frame: Frame) -> None:
        """A frame ingested while draining is journaled but never run:
        it is parked for the adopter, which replays it -- zero drop,
        no duplicate (nothing was delivered from here).  A frame with
        NO journal behind it (journal off, or a journal-off stream
        like the gateway's one-shots) has no adopter to park for:
        failing it loudly beats swallowing it into a client timeout."""
        if self.journal is None or not stream.journal:
            self._frame_fail(stream, frame,
                             "draining: no journal to hand off")
            return
        stream.frames.pop(frame.frame_id, None)
        self._qos_done(frame)
        # Consume the delivery slot silently so any in-flight
        # predecessors still flush their real responses in order.
        self._deliver(stream, frame, okay=False, skip=True)

    def _process_fault_probe(self, stream: Stream,
                             frame: Frame) -> bool:
        """Armed-chaos seam for the process-level fault points
        (tier-1's in-process realization; the multi-process driver
        uses real signals).  Returns True when the frame must not be
        processed (the process "died" -- the journaled frame replays
        on the adopter)."""
        rule = self._faults.should("process_kill", target=self.name,
                                   stream=stream.stream_id)
        if rule is not None:
            self.logger.warning("chaos: process_kill fired at %s; "
                                "dying uncleanly", self.name)
            self.kill()
            return True
        rule = self._faults.should("process_hang", target=self.name,
                                   stream=stream.stream_id)
        if rule is not None and rule.delay_ms:
            # The whole event loop stalls: parked frames age, peers'
            # deadlines fire -- exactly what a wedged process does.
            time.sleep(rule.delay_ms / 1000.0)
        return False

    def _note_pace(self, stream: Stream, frame: Frame,
                   paced: float) -> None:
        """Ingest blocked on the dispatch window: stamp the frame (the
        ``pacing`` critical-path bucket), the histogram and the ring."""
        paced_ms = paced * 1000.0
        frame.metrics["ingest_pace_ms"] = paced_ms
        if self.telemetry is not None:
            self.telemetry.registry.observe("ingest_pace_ms", paced_ms)
        self._rec("pace", stream.stream_id, frame.frame_id,
                  ms=paced_ms)

    def _note_fetch(self, stream: Stream, frame: Frame, name: str,
                    fetch_ms: float) -> None:
        """An engine-initiated counted ledger fetch ran for ``frame``
        on behalf of element ``name``: accumulate the ``fetch``
        critical-path bucket (``<name>_fetch_ms``) and the ring event.
        Loop-confined (every engine fetch site runs on the loop)."""
        if fetch_ms <= 0.0:
            return
        key = f"{name}_fetch_ms"
        frame.metrics[key] = frame.metrics.get(key, 0.0) + fetch_ms
        self._rec("fetch", stream.stream_id, frame.frame_id, name,
                  fetch_ms)

    def _assign_delivery_seq(self, stream: Stream, frame: Frame) -> None:
        """Under stage-parallel execution frames complete out of walk
        order; responses are re-ordered to ingest order (_deliver)."""
        if self.stage_scheduler is not None:
            frame.delivery_seq = stream.delivery_count
            stream.delivery_count += 1

    # -- the hot loop ------------------------------------------------------

    def _process_frame_common(self, stream: Stream, frame: Frame,
                              nodes=None, fuse=False):
        if stream.state not in (StreamState.START, StreamState.RUN):
            # The stream died while this frame was parked/queued: give
            # its stage credit back (the scheduler window is
            # pipeline-global -- leaking here would wedge EVERY stream
            # at that stage) and consume its delivery slot.
            stream.frames.pop(frame.frame_id, None)
            self._qos_done(frame)
            self._release_stage(stream, frame)
            self._deliver(stream, frame, okay=False, skip=True)
            return
        if self._past_deadline(frame):
            # Every walk entry and resume continuation passes through
            # here, so this one check enforces the deadline at ingest,
            # stage-hop and park-resume boundaries alike.
            self._deadline_fail(stream, frame)
            return
        stream.last_frame_time = time.monotonic()   # grace lease clock
        self.run_hook("pipeline.process_frame:0",
                      lambda: {"stream": stream.stream_id,
                               "frame": frame.frame_id})
        # Fusion applies to full-path walks and to resume continuations
        # that re-enter at a segment BOUNDARY (async/remote parks --
        # those elements never join a segment, so the suffix partitions
        # cleanly).  The retry paths pass fuse=False and execute
        # per-element: a frame must never resume into the middle of a
        # fused segment with half its outputs already mapped.
        fuse = fuse or nodes is None
        if nodes is None:
            nodes = self._stream_path(stream)
        frame.metrics.setdefault("time_pipeline_start", time.perf_counter())
        self._current_stream_ref = stream
        swag = frame.swag
        try:
            entries = self._fusion_entries(stream, nodes) if fuse \
                else list(nodes)
            index = 0
            while index < len(entries):
                entry = entries[index]
                if isinstance(entry, FusedSegment):
                    if entry.broken:
                        # Poisoned (build/trace failed earlier): splice
                        # the members back in permanently -- ``entries``
                        # IS the cached plan, so later frames skip the
                        # segment without re-failing.
                        entries[index:index + 1] = entry.nodes
                        continue
                    if self.stage_scheduler is not None \
                            and entry.stage_context is not None:
                        # Stage-local segment under stage-parallel
                        # execution: ONE dispatch on the stage's worker
                        # thread; the frame parks and the loop is free
                        # to walk other frames' stages meanwhile.
                        # ALWAYS via the worker (even when the frame no
                        # longer holds the stage credit, e.g. resumed
                        # past an in-stage async park): the single
                        # worker is what serializes the segment's
                        # unsynchronized JitCache across frames.
                        # Returns None (frame errored at resolve) or
                        # True (parked); either way this walk is done.
                        self._submit_stage_segment(stream, frame, entry)
                        return
                    outcome = self._run_fused_segment(stream, frame,
                                                      entry)
                    if outcome is None:
                        return        # frame errored (and responded)
                    if outcome is False:
                        entries[index:index + 1] = entry.nodes
                        continue      # fall back to per-element
                    index += 1
                    continue
                node = entry
                if self.stage_scheduler is not None \
                        and frame.stage != node.name \
                        and node.name in self.stage_placement.plans:
                    # Placed stage boundary: admission (credit window)
                    # and the rest of the walk happen on a fresh
                    # mailbox turn, so frame k+1's upstream stage work
                    # interleaves with frame k's downstream stage.
                    # ``stage_waiting`` marks the one in-flight
                    # admission post and the post carries the Frame
                    # object; enter_stage_frame discards any post that
                    # doesn't match both (duplicates, stale posts and
                    # queued tokens from a destroyed same-id stream).
                    frame.stage_waiting = node.name
                    frame.stage_wait_start = time.perf_counter()
                    # Aging clock for the QoS rank: how long THIS wait
                    # has lasted, not time since ingest -- a frame that
                    # just crossed a stage hasn't been starving.
                    frame.qos_wait_start = time.monotonic()
                    self._rec("stage_wait", stream.stream_id,
                              frame.frame_id, node.name)
                    self.post_self("enter_stage_frame",
                                   [stream.stream_id, frame.frame_id,
                                    node.name, False, frame])
                    return
                element = node.element
                if isinstance(element, RemoteStage):
                    # Leaving placed-stage-land: a frame parked at (or
                    # retrying discovery of) a remote stage must not
                    # pin its last placed stage's admission credit for
                    # the whole round trip -- a slow remote would wedge
                    # the window for every stream.
                    self._release_stage(stream, frame)
                    breaker = self._stage_breaker(node.name)
                    if breaker is not None and not breaker.allow():
                        # Open breaker: don't touch the wire.  Run the
                        # declared fallback element (degraded mode) or
                        # fail the FRAME fast -- the stream stays
                        # alive, and a later frame probes half-open.
                        ran = self._run_fallback(stream, frame, node)
                        if ran is None:
                            return        # frame errored in fallback
                        if ran:
                            index += 1
                            continue
                        if self.telemetry is not None:
                            self.telemetry.registry.count(
                                "breaker_rejects", stage=node.name)
                        self._rec("breaker_reject", stream.stream_id,
                                  frame.frame_id, node.name)
                        self._frame_fail(
                            stream, frame,
                            f"remote stage {node.name}: circuit "
                            f"breaker open")
                        return
                    if self._forward_frame(stream, frame, node):
                        frame.remote_retries = 0
                        return            # frame parked at remote stage
                    # Remote undiscovered yet: retry FROM THIS NODE --
                    # elements before it already ran and must not run
                    # again (their effects are in the swag).  The frame
                    # STAYS in stream.frames so graceful destroy_stream
                    # counts it as in-flight.  Exponential backoff with
                    # a cap (a fixed short retry forever is a silent
                    # hot loop), BOUNDED by ``remote_retry_limit``
                    # (0 = forever) so a permanently missing remote
                    # errors with a clear message instead of parking
                    # the frame for eternity, and a counted share
                    # metric so a missing remote stage is VISIBLE.
                    retry_limit = int(parse_number(
                        stream.parameters.get(
                            "remote_retry_limit",
                            self._pipeline_parameters.get(
                                "remote_retry_limit")),
                        REMOTE_RETRY_LIMIT_DEFAULT))
                    if retry_limit and frame.remote_retries \
                            >= retry_limit:
                        self._frame_error(
                            stream, frame,
                            f"remote stage {node.name} undiscovered "
                            f"after {frame.remote_retries} retries "
                            f"(remote_retry_limit={retry_limit}); "
                            f"is the remote pipeline running?")
                        return
                    delay = min(
                        _REMOTE_RETRY_BASE * (2 ** frame.remote_retries),
                        _REMOTE_RETRY_CAP)
                    frame.remote_retries += 1
                    frame.metrics["remote_retries"] = frame.remote_retries
                    self._remote_retries += 1
                    self.share["remote_stage_retries"] = \
                        self._remote_retries
                    if frame.remote_retries in (4, 8) \
                            or frame.remote_retries % 16 == 0:
                        self.logger.warning(
                            "stream %s frame %s: remote stage %s still "
                            "undiscovered after %d retries (next in "
                            "%.2f s)", stream.stream_id, frame.frame_id,
                            node.name, frame.remote_retries, delay)
                    self.post_self("retry_frame_at",
                                   [stream.stream_id, frame, node.name],
                                   delay=delay)
                    return
                inputs, missing, host_typed = self._map_in(node, swag,
                                                           frame=frame,
                                                           stream=stream)
                if missing:
                    self._frame_error(
                        stream, frame,
                        f"{node.name}: missing inputs {missing}")
                    return
                if self.stage_placement is not None \
                        and node.name in self.stage_placement.plans:
                    # Stage hop: reshard this stage's inputs onto its
                    # submesh (device-to-device over ICI; skipped per
                    # leaf when already resident there).  device_put is
                    # async -- the copy overlaps the upstream stage's
                    # next-frame compute; only the dispatch cost lands
                    # on the loop.  Host-typed inputs stay host-side --
                    # re-uploading what _map_in just fetched would undo
                    # the contract.
                    hop_start = time.perf_counter()
                    inputs.update(self.stage_placement.transfer(
                        {name: value for name, value in inputs.items()
                         if name not in host_typed}, node.name,
                        replica=frame.stage_replica
                        if frame.stage == node.name else None))
                    hop_ms = (time.perf_counter() - hop_start) * 1000.0
                    frame.metrics[f"{node.name}_hop_ms"] = hop_ms
                    self._rec("hop", stream.stream_id, frame.frame_id,
                              node.name, hop_ms)
                    self.run_hook("pipeline.stage_hop:0",
                                  lambda: {"stage": node.name,
                                           "stream": stream.stream_id,
                                           "frame": frame.frame_id,
                                           "ms": hop_ms})
                self.run_hook("pipeline.process_element:0",
                              lambda: {"element": node.name,
                                       "stream": stream.stream_id,
                                       "frame": frame.frame_id})
                if element.frame_is_async(stream):
                    self._submit_frame_async(stream, frame, node, inputs)
                    return        # frame parked at local async stage
                if self.stage_scheduler is not None \
                        and frame.stage == node.name:
                    # Synchronous placed-stage head under stage-parallel
                    # execution: run it on the stage's worker thread so
                    # the event loop keeps walking other frames while
                    # this stage's chips work -- cross-stage pipelining
                    # of plain synchronous elements.
                    self._submit_stage_frame(stream, frame, node, inputs)
                    return        # frame parked on the stage worker
                start = time.perf_counter()
                # Absolute start stamp: with overlapped frames, element
                # spans interleave across frames -- durations alone
                # cannot show (or test) that k+1's first element began
                # before k's last completed.
                frame.metrics[f"{node.name}_time_start"] = start
                self._rec("dispatch", stream.stream_id, frame.frame_id,
                          node.name)
                if _METRICS_MEMORY:
                    rss_before = process_memory_rss()
                ledger = self.transfer_ledger
                try:
                    if self._faults is not None:
                        self._inject_element_fault(node.name,
                                                   stream.stream_id)
                    if element.device_resident and ledger.active:
                        # Device elements run under the transfer guard:
                        # an implicit device->host sync inside one is a
                        # contract violation, not business as usual.
                        with ledger.guard():
                            result = element.process_frame(stream,
                                                           **inputs)
                    else:
                        result = element.process_frame(stream, **inputs)
                except Exception as error:
                    if ledger.is_guard_error(error):
                        ledger.record_implicit()
                    self.logger.exception("element %s raised", node.name)
                    self._rec("dispatch_done", stream.stream_id,
                              frame.frame_id, node.name,
                              (time.perf_counter() - start) * 1000.0,
                              {"status": "error"})
                    self._element_post_error(stream, frame, node.name,
                                             start)
                    if self._recover_after_dispatch_error(stream, frame):
                        return      # chips died: frame replayed/bounded
                    self._frame_error(stream, frame,
                                      f"{node.name}: {error}")
                    return
                frame.metrics[f"{node.name}_time"] = \
                    time.perf_counter() - start
                self._rec("dispatch_done", stream.stream_id,
                          frame.frame_id, node.name,
                          frame.metrics[f"{node.name}_time"] * 1000.0)
                if element.device_resident:
                    frame.metrics["device_dispatches"] = \
                        frame.metrics.get("device_dispatches", 0) + 1
                if _METRICS_MEMORY:
                    frame.metrics[f"{node.name}_memory"] = \
                        process_memory_rss() - rss_before
                event, outputs = result if isinstance(result, tuple) \
                    else (result, {})
                outputs = outputs or {}
                if ledger.active and outputs and not \
                        self._check_residency(stream, frame, node,
                                              element, outputs):
                    self._element_post_error(stream, frame, node.name,
                                             start)
                    return
                self.run_hook("pipeline.process_element_post:0",
                              lambda: {"element": node.name,
                                       "stream": stream.stream_id,
                                       "frame": frame.frame_id,
                                       "event": event,
                                       "time":
                                       frame.metrics[f"{node.name}_time"]})

                if event == StreamEvent.OKAY and isinstance(
                        element, PipelineElementLoop):
                    self._map_out(node, frame, outputs)
                    loop_start, found = element.get_parameter("loop_start")
                    if not found or loop_start not in self.graph:
                        self._frame_error(
                            stream, frame,
                            f"{node.name}: bad loop_start {loop_start!r}")
                        return
                    nodes = self.graph.get_path(loop_start)
                    entries = self._fusion_entries(stream, nodes) \
                        if fuse else list(nodes)
                    index = 0
                    continue
                if event in (StreamEvent.OKAY, StreamEvent.LOOP_END):
                    self._map_out(node, frame, outputs)
                    index += 1
                    continue
                if event == StreamEvent.DROP_FRAME:
                    frame.metrics["dropped"] = True
                    break
                if event == StreamEvent.STOP:
                    self._map_out(node, frame, outputs)
                    stream.state = StreamState.STOP
                    break
                if event == StreamEvent.ERROR:
                    diagnostic = outputs.get("diagnostic", "") \
                        if isinstance(outputs, dict) else ""
                    self._frame_error(stream, frame,
                                      f"{node.name}: {diagnostic}")
                    return
                self._frame_error(stream, frame,
                                  f"{node.name}: bad event {event!r}")
                return
            self._frame_done(stream, frame, nodes)
        finally:
            self._current_stream_ref = None

    # -- fused device segments (pipeline/fusion.py) ------------------------

    def _fusion_entries(self, stream: Stream, nodes) -> list:
        """The stream's fused execution plan for ``nodes``: Nodes and
        FusedSegments, partitioned once per path and memoized on the
        stream (``fuse: off`` short-circuits to the plain node list)."""
        if stream.fuse == "off":
            return list(nodes)
        key = tuple(node.name for node in nodes)
        plan = stream.fusion_plans.get(key)
        if plan is None:
            plan = partition(self, nodes, stream)
            stream.fusion_plans[key] = plan
            fused = [e for e in plan if isinstance(e, FusedSegment)]
            if fused:
                self.logger.info(
                    "stream %s: fused %d segment(s): %s",
                    stream.stream_id, len(fused),
                    ", ".join(s.name for s in fused))
        return plan

    def _segment_begin(self, stream: Stream, frame: Frame,
                       segment: FusedSegment):
        """Shared dispatch preamble for the inline and stage-worker
        segment paths: resolve inputs, pick donations, probe the
        compile, stamp spans, fire the enter hook.  Returns
        (resolved, donated, compiling, start), or None when the frame
        was errored on missing inputs."""
        resolved, missing = segment.resolve(frame.swag)
        if missing:
            self._frame_error(stream, frame,
                              f"{segment.name}: missing inputs {missing}")
            return None
        donated = segment.donate_keys(resolved, frame.swag,
                                      frame.produced)
        compiling = segment.would_compile(
            resolved, donated,
            replica=self._frame_replica_for(frame, segment))
        start = time.perf_counter()
        for node in segment.nodes:
            frame.metrics[f"{node.name}_time_start"] = start
        self.run_hook("pipeline.process_segment:0",
                      lambda: {"segment": segment.name,
                               "elements": [n.name for n in segment.nodes],
                               "stream": stream.stream_id,
                               "frame": frame.frame_id,
                               "compile": compiling})
        return resolved, donated, compiling, start

    @staticmethod
    def _frame_replica_for(frame: Frame, segment) -> int | None:
        """The replica submesh a stage-local segment dispatch belongs
        to: the frame's admitted replica when it holds the segment's
        stage credit, else None.  Keys the segment's JitCache per
        replica -- jax re-specializes executables per sharding, so
        replica A's warm signature is still a cold compile on replica
        B and the probe/poison logic must see it that way."""
        if segment.stage_context is not None \
                and frame.stage == segment.stage_context:
            return frame.stage_replica
        return None

    def _run_fused_segment(self, stream: Stream, frame: Frame,
                           segment: FusedSegment):
        """Execute a whole segment as ONE device dispatch.  Returns True
        on success, None when the frame was errored, False to fall back
        to per-element execution (first-call build/trace failure -- the
        segment is poisoned so later frames skip it outright)."""
        begun = self._segment_begin(stream, frame, segment)
        if begun is None:
            return None
        resolved, donated, compiling, start = begun
        ledger = self.transfer_ledger

        def post_hook(event):
            self.run_hook("pipeline.process_segment_post:0",
                          lambda: {"segment": segment.name,
                                   "stream": stream.stream_id,
                                   "frame": frame.frame_id,
                                   "event": event,
                                   "compile": compiling,
                                   "time": time.perf_counter() - start})

        self._rec("dispatch", stream.stream_id, frame.frame_id,
                  segment.name, info={"kind": "segment",
                                      "compile": compiling})
        try:
            if self._faults is not None:
                self._inject_segment_fault(segment.name,
                                           stream.stream_id)
            replica = self._frame_replica_for(frame, segment)
            if ledger.active:
                # The whole segment is device-element event-loop work:
                # one guard scope around the single dispatch.
                with ledger.guard():
                    out = segment.call(resolved, donated,
                                       replica=replica)
            else:
                out = segment.call(resolved, donated, replica=replica)
        except Exception as error:
            if ledger.is_guard_error(error):
                ledger.record_implicit()
            self._rec("dispatch_done", stream.stream_id,
                      frame.frame_id, segment.name,
                      (time.perf_counter() - start) * 1000.0,
                      {"status": "error"})
            post_hook(StreamEvent.ERROR)
            if compiling:
                # Build/trace failure on a fresh signature: the fused
                # path is an optimization, per-element execution is
                # ground truth -- poison and fall back (a genuine data
                # error will resurface there with a per-element
                # diagnostic).
                self.logger.exception(
                    "segment %s: trace/compile failed; falling back to "
                    "per-element execution", segment.name)
                segment.poison(f"trace/compile failed: {error}")
                return False
            self.logger.exception("segment %s raised", segment.name)
            if self._recover_after_dispatch_error(stream, frame):
                return None     # chips died: frame replayed/bounded
            self._frame_error(stream, frame, f"{segment.name}: {error}")
            return None
        elapsed = time.perf_counter() - start
        self._rec("dispatch_done", stream.stream_id, frame.frame_id,
                  segment.name, elapsed * 1000.0)
        return self._segment_finish(stream, frame, segment, out,
                                    resolved, donated, post_hook,
                                    elapsed)

    def _segment_finish(self, stream: Stream, frame: Frame,
                        segment: FusedSegment, out: dict, resolved: dict,
                        donated: set, post_hook, elapsed: float):
        """Map a completed segment dispatch out into the swag (shared by
        the inline path and the stage-worker continuation).  Returns
        True, or None when the frame was errored."""
        swag = frame.swag
        ledger = self.transfer_ledger
        # Donated buffers are dead: drop the stale qualified aliases
        # before map-out rewrites the bare keys, so nothing in the swag
        # can reach an invalidated buffer (DeviceWindow syncs swag
        # leaves at completion).
        for key in donated:
            swag.pop(f"{frame.produced[key]}.{key}", None)
        try:
            for step in segment.steps:
                outputs = {}
                for name in step.dfn.outputs:
                    outputs[name] = out[f"{step.node.name}.{name}"]
                for name, (kind, key) in step.pass_map.items():
                    outputs[name] = out[key] if kind == "trace" \
                        else resolved.get(key)
                if step.dfn.finalize is not None:
                    # The element's host postprocess: ONE counted fetch
                    # of its device slate at the segment boundary.
                    fetch_start = time.perf_counter()
                    fetched = ledger.fetch(
                        {name: out[f"{step.node.name}.{name}"]
                         for name in step.dfn.finalize_inputs})
                    self._note_fetch(
                        stream, frame, step.node.name,
                        (time.perf_counter() - fetch_start) * 1000.0)
                    outputs.update(step.dfn.finalize(fetched))
                self._map_out(step.node, frame, outputs)
                frame.metrics[f"{step.node.name}_time"] = 0.0
        except Exception as error:
            post_hook(StreamEvent.ERROR)
            self.logger.exception("segment %s map-out failed",
                                  segment.name)
            self._frame_error(stream, frame, f"{segment.name}: {error}")
            return None
        # The single dispatch's wall time lands on the tail element (so
        # per-element p50 keys stay populated); the members carry 0.0.
        frame.metrics[f"{segment.nodes[-1].name}_time"] = elapsed
        frame.metrics["fused_segments"] = \
            frame.metrics.get("fused_segments", 0) + 1
        frame.metrics["fused_elements"] = \
            frame.metrics.get("fused_elements", 0) + len(segment.nodes)
        frame.metrics["device_dispatches"] = \
            frame.metrics.get("device_dispatches", 0) + 1
        post_hook(StreamEvent.OKAY)
        return True

    # -- stage-parallel execution (pipeline/stages.py) ---------------------

    def enter_stage_frame(self, stream_id, frame_id, node_name,
                          from_queue=False, frame_ref=None):
        """Continuation: admit a frame into a placed stage's credit
        window and resume its walk at the stage head.  When the window
        is full the frame queues FIFO (still holding its PREVIOUS
        stage's credit, so backpressure propagates upstream) and is
        re-posted by the releasing frame; a popped waiter whose credit
        was stolen by an interleaving admission requeues at the FRONT,
        preserving queue (and per-stream frame) order."""
        stream = self.streams.get(str(stream_id))
        frame = stream.frames.get(int(frame_id)) \
            if stream is not None else None
        if frame is None or frame.paused_pe_name is not None \
                or frame.stage_waiting != node_name \
                or (frame_ref is not None and frame is not frame_ref):
            # Dead/stale/duplicate post: the frame vanished while
            # queued, was already admitted by an earlier post, or a
            # destroyed stream's post/token matched a RECREATED
            # stream's same-id frame (the Frame identity check catches
            # that even when the new frame waits for the same stage).
            # Acting on it would re-run elements or admit a frame out
            # of order; hand the slot (and any reservation the popped
            # token carried) to the next waiter so the queue never
            # starves.
            if from_queue and self.stage_scheduler is not None:
                self.stage_scheduler.cancel_reservation(node_name)
            self._pump_stage(node_name)
            return
        if self._past_deadline(frame):
            # Deadline enforcement at the admission boundary: an
            # expired frame must not take a stage credit.  Its own
            # reservation (when popped from the queue) goes back, and
            # the next waiter gets a chance at the freed capacity.
            if from_queue and self.stage_scheduler is not None:
                self.stage_scheduler.cancel_reservation(node_name)
            self._deadline_fail(stream, frame)
            self._pump_stage(node_name)
            return
        scheduler = self.stage_scheduler
        if scheduler is not None and frame.stage != node_name:
            group = scheduler.groups.get(node_name)
            if group is not None and group.all_dead():
                # Every replica dead and no rebuild yet: failing the
                # frame beats queueing it forever behind a stage that
                # cannot admit.
                if from_queue:
                    scheduler.cancel_reservation(node_name)
                self._frame_fail(stream, frame,
                                 f"stage {node_name}: all replicas "
                                 f"dead (awaiting rebuild)")
                return
            if group is not None:
                # QoS plane 3: latency-sensitive classes take the
                # least-loaded live replica instead of the cursor's
                # round-robin next.
                replica = scheduler.admit_replica(
                    node_name, reserved=bool(from_queue),
                    least_loaded=self.qos is not None
                    and self.qos.latency_sensitive(frame.qos_class))
                admitted = replica is not None
            else:
                replica = None
                admitted = scheduler.try_admit(node_name,
                                               reserved=bool(from_queue))
            if not admitted:
                scheduler.enqueue(node_name,
                                  [str(stream_id), int(frame_id),
                                   node_name, True, frame],
                                  front=bool(from_queue))
                return
            frame.stage_waiting = None
            self._release_stage(stream, frame)
            frame.stage = node_name
            frame.stage_replica = replica
            self._rec("admit", stream.stream_id, frame.frame_id,
                      node_name, info=None if replica is None
                      else {"replica": replica})
            if replica is not None:
                frame.metrics[f"stage_{node_name}_replica"] = replica
            frame.stage_generation = \
                self.stage_placement.generation \
                if self.stage_placement is not None else 0
            frame.metrics[f"stage_{node_name}_admit"] = \
                time.perf_counter()
            if frame.stage_wait_start is not None:
                # Admission wait: how long the frame sat behind the
                # stage's credit window (the telemetry plane rolls
                # these into the stage_admission_wait_ms histogram).
                frame.metrics[f"stage_{node_name}_wait_ms"] = \
                    (time.perf_counter() - frame.stage_wait_start) \
                    * 1000.0
                frame.stage_wait_start = None
            # Which placement generation this admission ran under --
            # the replace() test (and post-mortems) read it to prove a
            # frame re-entered on fresh submeshes, not a stale mesh.
            frame.metrics[f"stage_{node_name}_generation"] = \
                frame.stage_generation
            self.run_hook("pipeline.process_stage:0",
                          lambda: {"stage": node_name,
                                   "stream": stream.stream_id,
                                   "frame": frame.frame_id,
                                   "generation": frame.stage_generation})
            if self._faults is not None:
                rule = self._faults.should("stage_stall",
                                           target=node_name,
                                           stream=stream.stream_id)
                if rule is not None:
                    scheduler.executor(node_name, frame.stage_replica) \
                        .stall(rule.delay_ms / 1000.0)
        if not self._resume_walk_at(stream, frame, node_name, fuse=True):
            self._frame_error(
                stream, frame,
                f"enter_stage_frame: unknown node {node_name}")

    def _resume_walk_at(self, stream: Stream, frame: Frame,
                        node_name: str, fuse: bool) -> bool:
        """Resume a frame's walk at ``node_name`` on its stream path
        (stage admission, segment fallback, remote retry all land
        here).  Returns False when the node is not on the path -- the
        caller decides whether that errors the frame."""
        path = self._stream_path(stream)
        for index, node in enumerate(path):
            if node.name == node_name:
                self._process_frame_common(stream, frame,
                                           nodes=path[index:], fuse=fuse)
                return True
        return False

    def _release_stage(self, stream: Stream, frame: Frame,
                       ok: bool | None = True) -> None:
        """Return the frame's stage credit (next-stage admission, async
        park, completion, error, stream teardown) and wake the next
        queued frame.  For a replicated stage the credit goes back to
        the replica that admitted the frame, and ``ok`` carries the
        canary verdict: a half-open slot's canary frame succeeding
        closes the slot live, failing re-kills it, ``None`` (an
        administrative replay) leaves it half-open awaiting a real
        canary."""
        stage, frame.stage = frame.stage, None
        replica, frame.stage_replica = frame.stage_replica, None
        if ok is not True and stage is not None \
                and stage in frame.completed:
            # The frame failed AFTER this stage's head completed
            # (deadline while queued downstream, a later stage's
            # error): that is not this replica's verdict -- a half-open
            # slot whose canary ran the stage successfully closes live
            # even if the frame dies elsewhere.
            ok = True
        # A released frame is no longer waiting anywhere: its queued
        # token (if any) must read as stale when popped.
        frame.stage_waiting = None
        if stage is None or self.stage_scheduler is None:
            return
        admit = frame.metrics.get(f"stage_{stage}_admit")
        if admit is not None:
            frame.metrics[f"stage_{stage}_ms"] = \
                (time.perf_counter() - admit) * 1000.0
        self._rec("release", stream.stream_id, frame.frame_id, stage,
                  info=None if replica is None
                  else {"replica": replica})
        self.run_hook("pipeline.process_stage_post:0",
                      lambda: {"stage": stage,
                               "stream": stream.stream_id,
                               "frame": frame.frame_id,
                               "ms": frame.metrics.get(
                                   f"stage_{stage}_ms", 0.0)})
        waiter = self.stage_scheduler.release(stage, replica=replica,
                                              ok=ok)
        if waiter is not None:
            self.post_self("enter_stage_frame", list(waiter))

    def _pump_stage(self, stage: str) -> None:
        scheduler = self.stage_scheduler
        if scheduler is None:
            return
        waiter = scheduler.next_waiter(stage)
        if waiter is not None:
            self.post_self("enter_stage_frame", list(waiter))

    def _submit_stage_frame(self, stream: Stream, frame: Frame, node,
                            inputs: dict) -> None:
        """Run a synchronous placed-stage head element on the stage's
        worker thread: the frame parks exactly like an async stage and
        resumes through the mailbox, so while this stage's chips work
        on frame k the event loop walks frame k+1 into the upstream
        stage.  The single worker per stage keeps per-stream order."""
        element = node.element
        frame.paused_pe_name = node.name
        stream_id, frame_id = stream.stream_id, frame.frame_id
        node_name = node.name
        replica = frame.stage_replica   # replicated-stage submesh pick
        epoch = frame.replay_epoch      # stale after a replay
        submitted = time.perf_counter()
        frame.metrics[f"{node_name}_time_start"] = submitted
        self._rec("submit", stream_id, frame_id, node_name)
        if element.device_resident:
            frame.metrics["device_dispatches"] = \
                frame.metrics.get("device_dispatches", 0) + 1
        ledger = self.transfer_ledger

        def job():
            start = time.perf_counter()
            self._rec("dispatch", stream_id, frame_id, node_name,
                      info=None if replica is None
                      else {"replica": replica})
            _THREAD_STREAM.stream = stream
            # While this worker runs, ``self.plan`` on the stage's
            # elements IS the replica's submesh (tensor.TPUElement).
            _THREAD_STREAM.replica = None if replica is None \
                else (node_name, replica)
            try:
                if self._faults is not None:
                    self._inject_element_fault(node_name, stream_id)
                if element.device_resident and ledger.active:
                    with ledger.guard():
                        result = element.process_frame(stream, **inputs)
                else:
                    result = element.process_frame(stream, **inputs)
                event, outputs = result if isinstance(result, tuple) \
                    else (result, {})
                outputs = outputs or {}
            except Exception as error:
                if ledger.is_guard_error(error):
                    ledger.record_implicit()
                self.logger.exception(
                    "element %s raised (stage worker)", node_name)
                event, outputs = StreamEvent.ERROR, \
                    {"diagnostic": str(error)}
            finally:
                _THREAD_STREAM.stream = None
                _THREAD_STREAM.replica = None
            elapsed = time.perf_counter() - start
            self._rec("dispatch_done", stream_id, frame_id, node_name,
                      elapsed * 1000.0,
                      None if event != StreamEvent.ERROR
                      else {"status": "error"})
            self.post_self("resume_stage_frame",
                           [stream_id, frame_id, node_name, event,
                            outputs, start, elapsed, submitted,
                            frame, epoch])

        self.stage_scheduler.executor(node_name, replica).submit(job)

    def resume_stage_frame(self, stream_id, frame_id, node_name, event,
                           outputs, exec_start, elapsed, submitted,
                           frame_ref, epoch=None):
        """Continuation: a stage worker finished a synchronous placed
        element.  The post carries the Frame OBJECT it executed for: a
        stale post from a destroyed stream must never resume a
        recreated same-id stream's same-id frame (ids restart at 0).
        Re-stamps the span to the ACTUAL execution window (overlap
        assertions read ``*_time_start``) and records the queue window
        -- the time the frame's hop rode along behind the previous
        frame's stage compute."""
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return
        frame = stream.frames.get(int(frame_id))
        if frame is not frame_ref:
            return              # stale post from a prior incarnation
        if frame is not None \
                and epoch is not None and epoch != frame.replay_epoch:
            return              # pre-replay attempt: results are void
        if frame is not None and frame.paused_pe_name == node_name:
            frame.metrics[f"{node_name}_time_start"] = exec_start
            frame.metrics[f"{node_name}_queue_ms"] = \
                (exec_start - submitted) * 1000.0
        self.resume_frame_local(stream_id, frame_id, node_name, event,
                                outputs, elapsed, frame_ref)

    def _submit_stage_segment(self, stream: Stream, frame: Frame,
                              segment: FusedSegment):
        """Dispatch a stage-local fused segment on its stage's worker
        thread.  Returns True (parked), or None (frame errored at
        resolve)."""
        begun = self._segment_begin(stream, frame, segment)
        if begun is None:
            return None
        resolved, donated, _compiling, _submitted = begun
        frame.paused_pe_name = segment.name
        stream_id, frame_id = stream.stream_id, frame.frame_id
        self._rec("submit", stream_id, frame_id, segment.name)
        replica = self._frame_replica_for(frame, segment)
        epoch = frame.replay_epoch      # stale after a replay
        ledger = self.transfer_ledger

        def job():
            start = time.perf_counter()
            self._rec("dispatch", stream_id, frame_id, segment.name,
                      info={"kind": "segment"} if replica is None
                      else {"kind": "segment", "replica": replica})
            _THREAD_STREAM.stream = stream
            _THREAD_STREAM.replica = None if replica is None \
                else (segment.stage_context, replica)
            out, diagnostic = None, ""
            # Re-probe on the worker, where this segment's dispatches
            # are serialized: the loop-side probe goes stale when an
            # earlier frame's job is still compiling this signature
            # (window depth >= 2), and a stale True would let a
            # transient data error permanently poison the segment.
            compile_now = segment.would_compile(resolved, donated,
                                                replica=replica)
            try:
                if self._faults is not None:
                    self._inject_segment_fault(segment.name, stream_id)
                if ledger.active:
                    with ledger.guard():
                        out = segment.call(resolved, donated,
                                           replica=replica)
                else:
                    out = segment.call(resolved, donated,
                                       replica=replica)
            except Exception as error:
                if ledger.is_guard_error(error):
                    ledger.record_implicit()
                self.logger.exception(
                    "segment %s raised (stage worker)", segment.name)
                diagnostic = str(error)
            finally:
                _THREAD_STREAM.stream = None
                _THREAD_STREAM.replica = None
            elapsed = time.perf_counter() - start
            self._rec("dispatch_done", stream_id, frame_id,
                      segment.name, elapsed * 1000.0,
                      None if out is not None else {"status": "error"})
            self.post_self("resume_stage_segment",
                           [stream_id, frame_id, segment, out,
                            diagnostic, resolved, donated, compile_now,
                            start, elapsed, frame, epoch])

        self.stage_scheduler.executor(segment.stage_context,
                                      replica).submit(job)
        return True

    def resume_stage_segment(self, stream_id, frame_id, segment, out,
                             diagnostic, resolved, donated, compiling,
                             exec_start, elapsed, frame_ref,
                             epoch=None):
        """Continuation: a stage worker finished (or failed) a fused
        segment dispatch; map out and keep walking after the segment.
        Frame identity is validated (like resume_stage_frame) so stale
        posts from a destroyed same-id stream are discarded."""
        stream = self.streams.get(str(stream_id))
        frame = stream.frames.get(int(frame_id)) \
            if stream is not None else None
        if frame is None or frame is not frame_ref \
                or frame.paused_pe_name != segment.name:
            return
        if epoch is not None and epoch != frame.replay_epoch:
            return              # pre-replay attempt: results are void
        frame.paused_pe_name = None
        for node in segment.nodes:
            frame.metrics[f"{node.name}_time_start"] = exec_start

        def post_hook(event):
            self.run_hook("pipeline.process_segment_post:0",
                          lambda: {"segment": segment.name,
                                   "stream": stream.stream_id,
                                   "frame": frame.frame_id,
                                   "event": event,
                                   "compile": compiling,
                                   "time":
                                   time.perf_counter() - exec_start})

        if out is None:
            post_hook(StreamEvent.ERROR)
            if compiling:
                # First-signature trace/compile failure: poison the
                # segment and replay per-element -- the cached plan
                # splices broken segments on the next walk.
                self.logger.error(
                    "segment %s: stage-worker trace/compile failed; "
                    "falling back to per-element execution",
                    segment.name)
                segment.poison(f"stage-worker trace/compile failed: "
                               f"{diagnostic}")
                if self._resume_walk_at(stream, frame,
                                        segment.nodes[0].name,
                                        fuse=True):
                    return
            if self._recover_after_dispatch_error(stream, frame):
                return          # chips died: frame replayed/bounded
            self._frame_error(stream, frame,
                              f"{segment.name}: {diagnostic}")
            return
        if self._segment_finish(stream, frame, segment, out, resolved,
                                donated, post_hook, elapsed) is None:
            return
        nodes = self.graph.iterate_after(segment.nodes[-1].name,
                                         stream.graph_path)
        self._process_frame_common(stream, frame, nodes=nodes, fuse=True)

    # -- local async stage park / submit / resume --------------------------

    def _submit_frame_async(self, stream: Stream, frame: Frame, node,
                            inputs: dict) -> None:
        """Park the frame at a local async stage and hand it the inputs.
        The element calls ``complete(event, outputs)`` exactly once
        (from any thread); the frame resumes downstream via the actor
        mailbox -- the in-process twin of ``_forward_frame`` for remote
        stages, realizing dataflow over an async accelerator: detect of
        frame k+1 runs while the LLM decodes frame k, and a batching
        element sees requests from many frames/streams at once."""
        frame.paused_pe_name = node.name
        stream_id, frame_id = stream.stream_id, frame.frame_id
        node_name = node.name
        epoch = frame.replay_epoch      # stale after a replay
        start = time.perf_counter()
        frame.metrics[f"{node_name}_time_start"] = start
        self._rec("park", stream_id, frame_id, node_name,
                  info={"kind": "async"})
        if node.element.device_resident:
            frame.metrics["device_dispatches"] = \
                frame.metrics.get("device_dispatches", 0) + 1
        state = {"done": False}
        state_lock = threading.Lock()   # complete() may race itself
                                        # across threads; the resume
                                        # post must fire exactly once

        def complete(event, outputs=None):
            with state_lock:
                if state["done"]:
                    return              # double completion: ignore
                state["done"] = True
            self.post_self("resume_frame_local",
                           [stream_id, frame_id, node_name, event,
                            outputs or {},
                            time.perf_counter() - start, frame, epoch])

        ledger = self.transfer_ledger
        self._current_frame_ref = frame     # current_frame() for the
        try:                                # submit's element code
            if self._faults is not None:
                self._inject_element_fault(node_name, stream_id)
            if node.element.device_resident and ledger.active:
                # The submit path is device-element event-loop work
                # too: an implicit host sync here blocks every stream.
                with ledger.guard():
                    node.element.process_frame_start(stream, complete,
                                                     **inputs)
            else:
                node.element.process_frame_start(stream, complete,
                                                 **inputs)
            if frame.stage is not None:
                # Async elements own their admission discipline
                # (MicroBatcher max_batch, batcher slots) -- whether
                # the park is the stage head itself or an unplaced
                # async element deeper in the stage: holding the credit
                # through the park would cap cross-frame batching at
                # the window depth.
                self._release_stage(stream, frame)
        except Exception as error:
            if ledger.is_guard_error(error):
                ledger.record_implicit()
            self.logger.exception("element %s submit raised", node_name)
            with state_lock:
                state["done"] = True    # a late complete() must not win
            frame.paused_pe_name = None
            self._element_post_error(stream, frame, node_name, start)
            if self._recover_after_dispatch_error(stream, frame):
                return          # chips died: frame replayed/bounded
            self._frame_error(stream, frame, f"{node_name}: {error}")
        finally:
            self._current_frame_ref = None

    def resume_frame_local(self, stream_id, frame_id, node_name,
                           event, outputs, elapsed, frame_ref=None,
                           epoch=None):
        """Continuation: a parked async LOCAL stage completed (the local
        analogue of ``process_frame_response``).  ``frame_ref`` (when
        the poster holds the Frame object) guards against a stale
        completion resuming a REPLACEMENT frame parked at the same
        (stream_id, frame_id, node) -- e.g. after a wire re-ingest of a
        live frame id."""
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return                      # stream destroyed while parked
        frame = stream.frames.get(int(frame_id))
        if frame is None or frame.paused_pe_name != node_name:
            return
        if frame_ref is not None and frame is not frame_ref:
            return                      # stale post: frame was replaced
        if epoch is not None and epoch != frame.replay_epoch:
            return                      # pre-replay attempt: void
        frame.paused_pe_name = None
        frame.metrics[f"{node_name}_time"] = elapsed
        started = frame.metrics.get(f"{node_name}_time_start")
        if started is not None:
            # Resume lag: the element finished at started + elapsed;
            # the continuation then waited for the event loop.  That is
            # queue time (critical-path bucket) -- without it the
            # attribution misses exactly the loop-contention the
            # recorder's event timeline shows.  Accumulates with the
            # worker-queue stamp (same key) on the stage-worker path.
            lag_ms = (time.perf_counter() - started - elapsed) * 1000.0
            if lag_ms > 0.0:
                key = f"{node_name}_queue_ms"
                frame.metrics[key] = frame.metrics.get(key, 0.0) \
                    + lag_ms
        self._rec("resume", stream.stream_id, frame.frame_id,
                  node_name, elapsed * 1000.0)
        self.run_hook("pipeline.process_element_post:0",
                      lambda: {"element": node_name,
                               "stream": stream.stream_id,
                               "frame": frame.frame_id,
                               "event": event, "time": elapsed})
        outputs = outputs if isinstance(outputs, dict) else {}
        node = self.graph.get_node(node_name)
        if self.transfer_ledger.active and outputs and not \
                self._check_residency(stream, frame, node, node.element,
                                      outputs):
            return
        if event in (StreamEvent.OKAY, StreamEvent.LOOP_END):
            self._map_out(node, frame, outputs)
            nodes = self.graph.iterate_after(node_name, stream.graph_path)
            # The async park site is a partition boundary, so the
            # suffix re-enters the fused plan: device chains AFTER an
            # async stage still run as single dispatches.
            self._process_frame_common(stream, frame, nodes=nodes,
                                       fuse=True)
            return
        if event == StreamEvent.DROP_FRAME:
            frame.metrics["dropped"] = True
            self._frame_done(stream, frame, None)
            return
        if event == StreamEvent.STOP:
            self._map_out(node, frame, outputs)
            stream.state = StreamState.STOP
            self._frame_done(stream, frame, None)
            return
        diagnostic = outputs.get("diagnostic", "") \
            if event == StreamEvent.ERROR else f"bad event {event!r}"
        if event == StreamEvent.ERROR \
                and self._recover_after_dispatch_error(stream, frame):
            return              # chips died: frame replayed/bounded
        self._frame_error(stream, frame, f"{node_name}: {diagnostic}")

    def _readmit_frame(self, stream: Stream, frame: Frame) -> bool:
        """Re-register a retried/replayed frame with the stream.  A
        DIFFERENT live frame under the same id means this retry is
        stale (the stream was destroyed and recreated while the
        delayed post was pending) -- acting on it would corrupt the new
        incarnation.  A frame the stream no longer tracks re-enters
        with a FRESH delivery sequence: its old slot belongs to a dead
        incarnation's reorder buffer."""
        existing = stream.frames.get(frame.frame_id)
        if existing is not None:
            return existing is frame
        frame.delivery_seq = None
        self._assign_delivery_seq(stream, frame)
        stream.frames[frame.frame_id] = frame
        return True

    def retry_frame(self, stream_id, frame: Frame):
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return
        if not self._readmit_frame(stream, frame):
            return
        # Replays run per-element (explicit node list): a prior attempt
        # may have fused -- and donated -- its way through this swag, so
        # the retry must not assume segment inputs still exist as the
        # partitioner saw them.
        self._process_frame_common(stream, frame,
                                   nodes=self._stream_path(stream))

    def retry_frame_at(self, stream_id, frame: Frame, node_name: str):
        """Resume a frame at ``node_name`` (used when a remote stage was
        not yet discovered): earlier elements are not re-executed."""
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return
        if not self._readmit_frame(stream, frame):
            return
        # fuse=False: replays walk per-element (see retry_frame).
        if not self._resume_walk_at(stream, frame, node_name,
                                    fuse=False):
            self._frame_error(
                stream, frame,
                f"retry_frame_at: unknown node {node_name}")

    # -- name mapping ------------------------------------------------------

    def _map_in(self, node, swag: dict, frame: Frame | None = None,
                stream: Stream | None = None) -> tuple[dict, list, list]:
        """Returns (inputs, missing, host_typed): the host-typed names
        were materialized host-side and must stay there -- a placement
        transfer re-uploading them would undo the contract."""
        return self._map_in_for(node.element, node.properties or {},
                                swag, frame=frame, stream=stream)

    def _map_in_for(self, element, mapping: dict, swag: dict,
                    frame: Frame | None = None,
                    stream: Stream | None = None) \
            -> tuple[dict, list, list]:
        """`_map_in` against an explicit (element, mapping) pair -- the
        graph path shares it with breaker fallbacks, whose element is
        off-graph but resolves inputs through the remote node's
        mapping.  ``frame`` (when given) takes the host-typed fetch's
        cost as a ``fetch`` critical-path stamp."""
        inputs, missing, host_typed = {}, [], []
        host_inputs = element.host_inputs
        for io in (element.definition.input if element.definition else []):
            name = io["name"]
            key = mapping.get(name, name)
            if key in swag:
                inputs[name] = swag[key]
                if name in host_inputs or \
                        str(io.get("type", "")).rstrip("?") == "host":
                    host_typed.append(name)
            elif io.get("type", "").endswith("?") or "default" in io:
                inputs[name] = io.get("default")
            else:
                missing.append(name)
        if host_typed:
            # Explicitly host-typed inputs: THE sanctioned spot where
            # device-resident swag values reach the host mid-graph --
            # ONE counted fetch for all of them together, not an
            # implicit sync inside the element.
            fetch_start = time.perf_counter()
            inputs.update(self.transfer_ledger.fetch(
                {name: inputs[name] for name in host_typed}))
            if frame is not None and stream is not None:
                self._note_fetch(
                    stream, frame, element.name,
                    (time.perf_counter() - fetch_start) * 1000.0)
        return inputs, missing, host_typed

    def _element_post_error(self, stream: Stream, frame: Frame,
                            node_name: str, start: float):
        """Pair the enter hook on element-failure paths, so hook
        consumers (the profiler's open spans, recorders) never see an
        unmatched enter -- a dangling TraceAnnotation would nest the
        whole remaining trace under the dead element."""
        self.run_hook("pipeline.process_element_post:0",
                      lambda: {"element": node_name,
                               "stream": stream.stream_id,
                               "frame": frame.frame_id,
                               "event": StreamEvent.ERROR,
                               "time": time.perf_counter() - start})

    def _check_residency(self, stream: Stream, frame: Frame, node,
                         element, outputs: dict) -> bool:
        """Software half of the transfer guard (effective on backends
        where device->host is zero-copy and the jax guard cannot fire):
        declared-``tensor`` outputs must still be device-resident.
        Returns False when the frame was errored (policy disallow)."""
        if not element.device_resident:
            return True
        violations = self.transfer_ledger.residency_violations(element,
                                                               outputs)
        if not violations:
            return True
        self.transfer_ledger.record_implicit(len(violations))
        if self.transfer_ledger.policy == "disallow":
            self._frame_error(
                stream, frame,
                f"{node.name}: device outputs fetched to host: "
                f"{violations} (transfer_guard=disallow)")
            return False
        self.logger.warning("%s: device outputs fetched to host: %s",
                            node.name, violations)
        return True

    @staticmethod
    def _map_out(node, frame: Frame, outputs: dict):
        swag = frame.swag
        for name, value in outputs.items():
            swag[name] = value
            swag[f"{node.name}.{name}"] = value
            # Provenance for fused-segment donation: only values an
            # element of THIS frame produced are ever donatable.
            frame.produced[name] = node.name
        # Replay frontier (ISSUE 5): outputs accepted -> this element
        # never re-executes when the frame replays across a device
        # replacement.
        frame.completed.add(node.name)

    # -- completion / errors / responses ----------------------------------

    def _frame_done(self, stream: Stream, frame: Frame, nodes):
        if self._past_deadline(frame):
            # Deadline enforcement at delivery: the work finished, but
            # late IS wrong under an SLO -- the slot carries a deadline
            # error, not a stale result.
            self._deadline_fail(stream, frame)
            return
        frame.metrics["time_pipeline"] = (
            time.perf_counter() - frame.metrics["time_pipeline_start"])
        stream.last_frame_time = time.monotonic()   # grace lease clock
        stream.frames.pop(frame.frame_id, None)
        self._qos_done(frame)
        self._rec("done", stream.stream_id, frame.frame_id,
                  ms=frame.metrics["time_pipeline"] * 1000.0,
                  info={"ok": True})
        self._release_stage(stream, frame)
        self._record_stage_costs(frame)
        # The frame COMPLETES without a host sync: its device leaves may
        # still be computing (async dispatch).  Note them so ingest
        # pacing bounds how far dispatch runs ahead of compute.
        stream.device_window.note(frame.frame_id, frame.swag)
        self._frames_processed += 1
        self.share["frames_processed"] = self._frames_processed
        # Compiled-call + fusion accounting on the share dict (the
        # transfer_stats()-style surface the dashboard and bench read).
        # Totals only -- plain attribute sums, no per-element breakdown
        # dicts on the per-frame completion path (jit_stats() builds
        # those on demand).
        hits = misses = entries = dispatches = 0
        for node in self.graph.nodes():
            cache = getattr(node.element, "jit_cache", None)
            if cache is not None:
                hits += cache.hits
                misses += cache.misses
                entries += cache.entries
        for segment in self.fused_segments:
            cache = segment.jit_cache
            hits += cache.hits
            misses += cache.misses
            entries += cache.entries
            dispatches += segment.calls
        self.share["jit_cache_hits"] = hits
        self.share["jit_cache_misses"] = misses
        self.share["jit_cache_entries"] = entries
        self.share["fused_segments"] = len(self.fused_segments)
        self.share["fused_dispatches"] = dispatches
        if self.telemetry is not None:
            # BEFORE delivery: the root span (and any remote spans)
            # must be on frame.spans when _respond encodes them back
            # to a forwarding origin.
            self.telemetry.frame_finished(stream, frame, okay=True)
        dropped = bool(frame.metrics.get("dropped"))
        if dropped and not self._draining \
                and self.journal is not None and stream.journal:
            # A dropped frame is CONSUMED: prune it, or it stays
            # 'undelivered' forever -- wedging the done_upto
            # watermark, growing the journal unboundedly, and
            # replaying every historically dropped frame on adoption.
            # EXCEPT while draining: the LLM drain migration drops
            # its parked frames precisely so the adopter replays them.
            self.journal.frame_done(stream.stream_id, frame.frame_id,
                                    ok=True)
        self._deliver(stream, frame, okay=True, skip=dropped)
        if stream.state == StreamState.STOP:
            self.post_self("destroy_stream", [stream.stream_id, True])

    def _record_stage_costs(self, frame: Frame) -> None:
        """Feed the placement's cost profile from the frame's measured
        stage-head element spans, so ``devices: auto`` splits track the
        workload (and re-balance at the next replace())."""
        placement = self.stage_placement
        if placement is None:
            return
        for stage in placement.plans:
            if stage not in self.graph:
                continue
            if self.graph.get_node(stage).element.is_async:
                # An async head's span is completion-minus-submit --
                # batch/queue wait included, which GROWS under load and
                # would steer the auto split toward the waiting stage.
                continue
            elapsed = frame.metrics.get(f"{stage}_time")
            if elapsed:
                placement.record_cost(stage, float(elapsed))

    def _deliver(self, stream: Stream, frame: Frame, okay: bool,
                 diagnostic: str = "", skip: bool = False) -> None:
        """In-order per-stream delivery: under stage-parallel execution
        frames complete out of ingest order (per-stage workers, async
        stages), so responses buffer until every predecessor responded.
        ``skip`` consumes the sequence slot without responding (dropped
        frames)."""
        seq = frame.delivery_seq
        if seq is None:
            if not skip:
                self._respond(stream, frame, okay, diagnostic)
            return
        stream.delivery_pending[seq] = \
            None if skip else (frame, okay, diagnostic)
        self._flush_delivery(stream)

    def _flush_delivery(self, stream: Stream) -> None:
        while stream.delivery_next in stream.delivery_pending:
            item = stream.delivery_pending.pop(stream.delivery_next)
            stream.delivery_next += 1
            if item is not None:
                pending_frame, okay, diagnostic = item
                self._respond(stream, pending_frame, okay, diagnostic)

    def _frame_error(self, stream: Stream, frame: Frame, diagnostic: str):
        """Fatal frame failure: the stream enters ERROR and tears down
        (reference semantics -- an element error poisons the stream)."""
        self.logger.error("stream %s frame %s: %s",
                          stream.stream_id, frame.frame_id, diagnostic)
        self._blackbox("stream_error", stream.stream_id,
                       frame.frame_id, detail=diagnostic)
        self._finish_failed_frame(stream, frame, diagnostic)
        stream.state = StreamState.ERROR
        self.post_self("destroy_stream", [stream.stream_id])

    def _frame_fail(self, stream: Stream, frame: Frame, diagnostic: str):
        """Per-frame failure on a HEALTHY stream (deadline miss,
        overload shed, open circuit breaker): the frame delivers an
        error in its reorder slot, the stream keeps running.  This is
        the load-shedding contract -- an SLO miss must not amplify into
        a stream teardown."""
        self.logger.warning("stream %s frame %s: %s",
                            stream.stream_id, frame.frame_id, diagnostic)
        self._finish_failed_frame(stream, frame, diagnostic)

    def _finish_failed_frame(self, stream: Stream, frame: Frame,
                             diagnostic: str):
        stream.frames.pop(frame.frame_id, None)
        self._qos_done(frame)
        self._rec("done", stream.stream_id, frame.frame_id,
                  info={"ok": False, "error": str(diagnostic)[:200]})
        # ok=False: when the failed frame was a half-open replica's
        # canary, its failure is the verdict -- the slot re-kills
        # instead of re-admitting a replica that still cannot serve.
        self._release_stage(stream, frame, ok=False)
        if self.telemetry is not None:
            self.telemetry.frame_finished(stream, frame, okay=False)
        if frame.delivery_seq is not None:
            # Deliver the error IN its slot so already-completed
            # successors' buffered okay-responses flush behind it
            # instead of being dropped; whatever stays gapped (a
            # predecessor still in flight) drains at destroy.
            stream.delivery_pending[frame.delivery_seq] = \
                (frame, False, diagnostic)
            self._flush_delivery(stream)
        else:
            self._respond(stream, frame, okay=False,
                          diagnostic=diagnostic)

    def _respond(self, stream: Stream, frame: Frame, okay: bool,
                 diagnostic: str = ""):
        if frame.response_topic:
            bare_swag = {k: v for k, v in frame.swag.items()
                         if "." not in k}
            # Process boundary: THE sink where device-resident swag
            # values are fetched -- one explicit counted device_get for
            # the whole response, then the host-side codec.
            bare_swag = self.transfer_ledger.fetch(bare_swag)
            header = {"stream_id": stream.stream_id,
                      "frame_id": frame.frame_id,
                      "okay": okay, "diagnostic": diagnostic}
            if frame.trace_remote and frame.spans:
                # Forwarded frame: return this process's spans so the
                # ORIGIN reconstructs the whole distributed trace.
                header["spans"] = encode_spans(frame.spans)
            # Response tensors ride the origin's pipe when it
            # advertised one (pipe_reply header); failures re-inline
            # them into the MQTT payload, counted.
            # Site key is the PEER endpoint, not the stream id: the
            # once-per-site fallback log (and its dedup set) must stay
            # bounded under thousands of short streams.
            body, pipe_bytes = (bare_swag, None) \
                if self._data_plane_mode == "mqtt" or not okay \
                else self._pipe_ship(frame.pipe_reply, bare_swag,
                                     header,
                                     f"response to "
                                     f"{frame.pipe_reply or 'origin'}")
            payload = generate("process_frame_response",
                               [header, encode_frame_data(body)])
            self.runtime.message.publish(frame.response_topic, payload)
            self._count_plane(pipe_bytes, len(payload))
        if stream.queue_response is not None:
            # Snapshot: queue consumers read from other threads, and
            # the live dict must stay loop-confined (see Frame.metrics).
            stream.queue_response.put(
                (stream.stream_id, frame.frame_id,
                 dict(frame.swag), dict(frame.metrics), okay,
                 diagnostic))
        if self.journal is not None and stream.journal:
            # Delivery is the journal's prune point -- appended AFTER
            # the send, deliberately: a crash between the two turns
            # into a duplicate replay the gateway's seq dedupe drops,
            # where the reverse order would be a silent loss (marked
            # delivered, never sent, excluded from replay).
            self.journal.frame_done(stream.stream_id, frame.frame_id,
                                    ok=okay)

    # -- remote stage park / forward / resume ------------------------------

    def _forward_frame(self, stream: Stream, frame: Frame, node,
                       force_mqtt: bool = False) -> bool:
        stage: RemoteStage = node.element
        if stage.remote_topic_path is None:
            return False
        frame.paused_pe_name = node.name
        inputs, _, _ = self._map_in(node, frame.swag, frame=frame,
                                    stream=stream)
        # Forward ALL mapped inputs; the remote pipeline maps what it needs.
        # Process boundary: explicit single fetch before the host codec.
        fetch_start = time.perf_counter()
        forwarded = self.transfer_ledger.fetch(
            inputs if inputs else {
                k: v for k, v in frame.swag.items() if "." not in k})
        self._note_fetch(stream, frame, node.name,
                         (time.perf_counter() - fetch_start) * 1000.0)
        header = {"stream_id": stream.stream_id,
                  "frame_id": frame.frame_id,
                  "response_topic": self.topic_in}
        if self._data_endpoint is not None:
            # Advertise our endpoint so the response's tensors come
            # back over the pipe too (the peer negotiates down to MQTT
            # when it cannot, or when this send's twin fails there).
            header["pipe_reply"] = self._data_endpoint.location
        if self.telemetry is not None and frame.trace_id is not None:
            # Trace context rides the hop: the remote pipeline stamps
            # its spans under this hop span's id and returns them in
            # the response, so one trace_id covers both processes.  A
            # RE-forward (remote lost mid-park, frame replayed) reuses
            # the still-open hop span rather than leaking it.
            if frame.remote_span is None \
                    or frame.remote_span[0] != node.name:
                frame.remote_span = (node.name, mint_id(), time.time())
            header["trace_id"] = frame.trace_id
            header["trace_parent"] = frame.remote_span[1]
        # Data plane (ISSUE 9): tensors over the peer's advertised
        # pipe, control envelope (+ token) on MQTT; any pipe problem
        # re-inlines the tensors into the MQTT payload -- the frame
        # always goes out exactly once.
        body, pipe_bytes = (forwarded, None) \
            if force_mqtt or self._data_plane_mode == "mqtt" \
            else self._pipe_ship(stage.remote_pipe, forwarded, header,
                                 f"forward to {node.name}")
        payload = generate("process_frame",
                           [header, encode_frame_data(body)])
        self.runtime.message.publish(f"{stage.remote_topic_path}/in",
                                     payload)
        self._count_plane(pipe_bytes, len(payload))
        self._rec("forward", stream.stream_id, frame.frame_id,
                  node.name,
                  info={"path": "mqtt" if pipe_bytes is None
                        else "pipe"})
        return True

    def process_frame_response(self, stream_dict=None, frame_data=None):
        """Continuation: a parked frame's remote outputs arrived
        (reference pipeline.py:1218-1221,1452-1455).  A ``pipe_token``
        header means the output tensors rode the binary data plane:
        claim them (deferring until they land, dropping after the
        claim timeout -- the parked frame then recovers through its
        deadline/breaker exactly as for a dropped response)."""
        stream_dict = dict(stream_dict or {})
        stream_id = str(stream_dict.get("stream_id", DEFAULT_STREAM_ID))
        stream = self.streams.get(stream_id)
        if stream is None:
            return
        pipe_claimed = self._claim_pipe_response(stream_dict,
                                                 dict(frame_data or {}))
        if pipe_claimed is None:
            return              # deferred behind the watch, or dropped
        frame_id = int(parse_number(stream_dict.get("frame_id"), -1))
        frame = stream.frames.get(frame_id)
        if frame is None or frame.paused_pe_name is None:
            return
        if frame.paused_pe_name not in self.graph or not isinstance(
                self.graph.get_node(frame.paused_pe_name).element,
                RemoteStage):
            # Duplicate or late response (wire_dup fault, MQTT QoS1
            # redelivery): the frame has moved on and is parked at a
            # LOCAL element/segment now -- mapping remote outputs under
            # that node would silently replace its real result.
            return
        okay = str(stream_dict.get("okay", "true")).lower() != "false"
        round_ms = None
        if self.telemetry is not None:
            # Close the hop span and merge the remote pipeline's spans
            # BEFORE the okay branch: an errored remote round trip
            # still belongs on the trace.
            if frame.remote_span is not None:
                node_name, span_id, started = frame.remote_span
                frame.remote_span = None
                round_ms = (time.time() - started) * 1000.0
                # Critical-path ``pipe`` bucket: the whole remote round
                # trip (wire both ways + the remote's own compute --
                # its internal split is in the returned spans).
                key = f"remote_{node_name}_ms"
                frame.metrics[key] = \
                    frame.metrics.get(key, 0.0) + round_ms
                frame.spans.append(make_span(
                    frame.trace_id or "", span_id, frame.trace_root,
                    f"remote:{node_name}", "remote", self.name,
                    stream.stream_id, frame.frame_id, started,
                    round_ms, status="ok" if okay else "error"))
            remote_spans = stream_dict.get("spans")
            if remote_spans:
                frame.spans.extend(decode_spans(remote_spans))
        self._rec("response", stream.stream_id, frame.frame_id,
                  frame.paused_pe_name, round_ms,
                  None if okay else {"status": "error"})
        breaker = self._stage_breaker(frame.paused_pe_name) \
            if frame.paused_pe_name in self.graph else None
        if not okay:
            if str(stream_dict.get("pipe_retry", "")).strip().lower() \
                    in ("true", "1") \
                    and frame.metrics.get("pipe_retries", 0) < 1:
                # The REMOTE never got our pipe tensors (its claim
                # timed out): not a remote failure -- a data-plane
                # loss.  Re-forward this frame with the tensors inlined
                # into the MQTT payload, once; the breaker is not
                # charged (the remote answered, the pipe died).
                node = self.graph.get_node(frame.paused_pe_name)
                frame.metrics["pipe_retries"] = \
                    frame.metrics.get("pipe_retries", 0) + 1
                self._count_pipe_fallback(
                    f"re-forward to {node.name}",
                    "peer claim timed out; resending over MQTT")
                if self._forward_frame(stream, frame, node,
                                       force_mqtt=True):
                    return
            if breaker is not None:
                self._breaker_failure(frame.paused_pe_name, breaker,
                                      stream.stream_id, frame.frame_id)
            self._frame_error(stream, frame,
                              f"remote {frame.paused_pe_name}: "
                              f"{stream_dict.get('diagnostic', '')}")
            return
        try:
            outputs = decode_frame_data(dict(frame_data or {}))
            outputs.update(pipe_claimed)
        except Exception as error:
            # A corrupt-but-parseable response payload: counts against
            # the stage's breaker like any other remote failure.
            if breaker is not None:
                self._breaker_failure(frame.paused_pe_name, breaker,
                                      stream.stream_id, frame.frame_id)
            self._frame_error(stream, frame,
                              f"remote {frame.paused_pe_name}: "
                              f"undecodable response ({error})")
            return
        if breaker is not None:
            self._breaker_success(frame.paused_pe_name, breaker,
                                  stream.stream_id, frame.frame_id)
        node = self.graph.get_node(frame.paused_pe_name)
        self._map_out(node, frame, outputs)
        resume_after = frame.paused_pe_name
        frame.paused_pe_name = None
        nodes = self.graph.iterate_after(resume_after, stream.graph_path)
        # RemoteStage parks are partition boundaries too: the suffix
        # after a remote hop fuses like any full-path walk.
        self._process_frame_common(stream, frame, nodes=nodes, fuse=True)

    # -- frame generators (source elements) --------------------------------

    def create_frame_local(self, stream: Stream, frame_data: dict):
        self.post_self("ingest_local", [stream.stream_id, frame_data, None])

    def create_frame_generator(self, stream: Stream, element,
                               frame_generator, rate: float | None):
        stop_event = threading.Event()
        stream.generator_handles.append(stop_event)
        interval = (1.0 / rate) if rate else 0.0
        engine = self.runtime.engine
        mailbox = self._mailbox_in

        def pump():
            next_due = time.monotonic()
            while not stop_event.is_set() and stream.state in (
                    StreamState.START, StreamState.RUN):
                # Backpressure counts queued AND parked frames: async
                # stages hold frames out of the mailbox while in flight,
                # and a source must not outrun them unboundedly.
                if engine.mailbox_size(mailbox) + stream.in_flight \
                        >= _BACKPRESSURE_DEPTH:
                    time.sleep(_BACKPRESSURE_SLEEP)
                    continue
                try:
                    event, frame_data = frame_generator(stream)
                except Exception:
                    self.logger.exception("frame generator %s raised",
                                          element.name)
                    break
                if event == StreamEvent.OKAY:
                    self.post_self("ingest_local",
                                   [stream.stream_id, frame_data, None])
                elif event == StreamEvent.NO_FRAME:
                    time.sleep(0.02)
                    continue
                else:
                    self.post_self("destroy_stream",
                                   [stream.stream_id, True])
                    break
                if interval:
                    next_due += interval
                    delay = next_due - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
            try:
                stream.generator_handles.remove(stop_event)
            except ValueError:
                pass

        thread = threading.Thread(
            target=pump, daemon=True,
            name=f"frame-gen-{self.name}-{element.name}")
        thread.start()

    def stop(self):
        self._cancel_health_timer()     # controller timer included
        self.disarm_faults()
        controller = getattr(self, "controller", None)
        if controller is not None:
            if controller.supervisor is not None:
                controller.supervisor.stop_all()
            self.controller = None
        fleet = getattr(self, "fleet_collector", None)
        if fleet is not None:
            fleet.stop()
            self.fleet_collector = None
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.gateway is not None:
            # Before streams: a live WebSocket session must stop
            # feeding frames before its stream tears down under it.
            self.gateway.stop()
            self.gateway = None
        for stream_id in list(self.streams):
            self._destroy_stream_now(stream_id)
        if self.stage_scheduler is not None:
            self.stage_scheduler.stop()
        if self._data_endpoint is not None:
            self._data_endpoint.close()
            self._data_endpoint = None
        for sender in self._pipe_senders.values():
            sender.close()
        if self.journal is not None:
            self.journal.close()
        super().stop()


def create_pipeline(definition_pathname: str, name=None, runtime=None,
                    preflight: str | None = None) -> Pipeline:
    definition = load_pipeline_definition(definition_pathname)
    return Pipeline(definition, name=name, runtime=runtime,
                    preflight=preflight)
