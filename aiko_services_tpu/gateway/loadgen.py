"""Open-loop load generator for the gateway (ISSUE 12 satellite).

Drives mixed-tenant traffic at FIXED arrival rates against a running
:class:`~.server.GatewayServer`: one WebSocket session per traffic
spec, a sender thread that ships frames on the open-loop schedule
(``start + i/rate`` -- it never waits for completions, so queueing
delay shows up as latency instead of silently throttling the offered
load, the classic closed-loop benchmarking mistake), and a receiver
thread that tallies results, rejections and backpressure.

Latencies come from the gateway's own ``e2e_ms`` stamp (admission ->
result, the server-side view of the session SLO); per-class p50/p99,
goodput (ok results / wall), and shed/reject counts aggregate across
sessions.  Reused by ``bench_pipeline_gateway``, the ``loadgen`` CLI
command, and the overload fairness tests.
"""

from __future__ import annotations

import threading
import time

from .client import GatewayClient
from . import ws

__all__ = ["run_loadgen", "LoadSpec"]


class LoadSpec:
    """One tenant's traffic: ``rate`` frames/s open-loop for
    ``frames`` frames, under ``qos_class`` with an optional per-frame
    ``deadline_ms``.  ``data`` is the frame payload (dict) or a
    callable ``(index) -> dict``."""

    def __init__(self, tenant: str, qos_class: str, rate: float,
                 frames: int, data=None, deadline_ms: float = 0.0,
                 window: int | None = None, session: str | None = None):
        self.tenant = tenant
        self.qos_class = qos_class
        self.rate = float(rate)
        self.frames = int(frames)
        self.data = data if data is not None else {"x": 1.0}
        self.deadline_ms = float(deadline_ms)
        self.window = window
        self.session = session or f"lg-{tenant}-{qos_class}"


def _blank_bucket() -> dict:
    return {"sent": 0, "ok": 0, "errors": 0, "shed": 0, "deadline": 0,
            "rejected": 0, "busy": 0, "latencies_ms": []}


def _merge_result(bucket: dict, message: dict) -> None:
    if message.get("ok"):
        bucket["ok"] += 1
        bucket["latencies_ms"].append(float(message.get("e2e_ms", 0.0)))
    else:
        bucket["errors"] += 1
        diagnostic = str(message.get("diagnostic", ""))
        if "shed" in diagnostic:
            bucket["shed"] += 1
        elif "deadline" in diagnostic:
            bucket["deadline"] += 1


def _quantile(values: list, q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    index = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[index]


def _drive(host: str, port: int, spec: LoadSpec, bucket: dict,
           errors: list) -> None:
    try:
        client = GatewayClient(host, port)
        client.open(session=spec.session, tenant=spec.tenant,
                    qos_class=spec.qos_class,
                    deadline_ms=spec.deadline_ms or None,
                    window=spec.window)
    except Exception as error:
        errors.append(f"{spec.tenant}: open failed: {error}")
        return
    done = threading.Event()
    outstanding = {"count": 0}
    lock = threading.Lock()

    def receive():
        while True:
            try:
                message = client.recv(timeout=30.0)
            except (ws.WsClosed, OSError):
                return
            op = message.get("op")
            with lock:
                if op == "result":
                    _merge_result(bucket, message)
                    outstanding["count"] -= 1
                elif op == "rejected":
                    bucket["rejected"] += 1
                    outstanding["count"] -= 1
                elif op == "busy":
                    bucket["busy"] += 1
                    outstanding["count"] -= 1
                else:
                    continue
                if done.is_set() and outstanding["count"] <= 0:
                    return

    receiver = threading.Thread(target=receive, daemon=True,
                                name=f"loadgen-recv-{spec.tenant}")
    receiver.start()
    start = time.monotonic()
    for index in range(spec.frames):
        due = start + index / spec.rate if spec.rate > 0 else start
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        payload = spec.data(index) if callable(spec.data) \
            else dict(spec.data)
        with lock:
            bucket["sent"] += 1
            outstanding["count"] += 1
        try:
            client.send_frame(payload)
        except OSError as error:
            errors.append(f"{spec.tenant}: send failed: {error}")
            break
    done.set()
    receiver.join(timeout=60.0)
    client.close()


def run_loadgen(host: str, port: int, specs: list) -> dict:
    """Run every spec concurrently; -> per-class and per-tenant
    aggregates with p50/p99 latency, goodput and shed/reject counts."""
    buckets = [_blank_bucket() for _ in specs]
    errors: list = []
    started = time.monotonic()
    threads = [threading.Thread(target=_drive,
                                args=(host, port, spec, bucket, errors),
                                daemon=True,
                                name=f"loadgen-{spec.tenant}")
               for spec, bucket in zip(specs, buckets)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=300.0)
    wall_s = max(1e-9, time.monotonic() - started)

    def aggregate(group_of) -> dict:
        groups: dict = {}
        for spec, bucket in zip(specs, buckets):
            entry = groups.setdefault(group_of(spec), _blank_bucket())
            for key, value in bucket.items():
                if key == "latencies_ms":
                    entry[key] = entry[key] + value
                else:
                    entry[key] += value
        result = {}
        for name, entry in groups.items():
            latencies = entry.pop("latencies_ms")
            entry["p50_ms"] = round(_quantile(latencies, 0.50), 3)
            entry["p99_ms"] = round(_quantile(latencies, 0.99), 3)
            entry["goodput_fps"] = round(entry["ok"] / wall_s, 3)
            result[name] = entry
        return result

    return {"wall_s": round(wall_s, 3),
            "classes": aggregate(lambda spec: spec.qos_class),
            "tenants": aggregate(lambda spec: spec.tenant),
            "errors": errors}
