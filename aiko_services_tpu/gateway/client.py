"""Stdlib WebSocket client for the gateway session protocol.

The tier-1 acceptance path ("a real WebSocket client streams N frames
through a placed pipeline and receives N in-order results") runs this
client against :class:`~.server.GatewayServer` over loopback; the
load generator drives many of them concurrently.  It is a thin,
synchronous wrapper over the shared RFC 6455 codec in
:mod:`~aiko_services_tpu.gateway.ws` -- client side, so every frame it
sends is masked.
"""

from __future__ import annotations

import json
import socket

from . import ws

__all__ = ["GatewayClient"]


class GatewayClient:
    def __init__(self, host: str, port: int,
                 path: str = "/v1/stream",
                 timeout: float | None = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        ws.client_handshake(self.sock, host, port, path)
        self.session_id: str | None = None
        self.token: str | None = None

    # -- protocol ----------------------------------------------------------

    def send(self, payload: dict) -> None:
        ws.send_frame(self.sock, json.dumps(payload), mask=True)

    def recv(self, timeout: float | None = None) -> dict:
        """Next protocol message (result/busy/rejected/...); raises
        ``ws.WsClosed`` when the server closes, ``socket.timeout`` on
        the deadline."""
        if timeout is not None:
            self.sock.settimeout(timeout)
        _, payload = ws.recv_message(self.sock, mask_replies=True)
        return json.loads(payload.decode())

    def open(self, session: str | None = None, tenant: str = "default",
             qos_class: str | None = None,
             deadline_ms: float | None = None,
             window: int | None = None,
             token: str | None = None,
             timeout: float | None = 10.0) -> dict:
        """Open (or, with the ``token`` from a previous ``opened``
        ack, ATTACH to) a session.  The returned reply carries the
        session's attach token -- also kept on ``self.token``."""
        message: dict = {"op": "open", "tenant": tenant}
        if session is not None:
            message["session"] = session
        if qos_class is not None:
            message["class"] = qos_class
        if deadline_ms is not None:
            message["deadline_ms"] = deadline_ms
        if window is not None:
            message["window"] = window
        if token is not None:
            message["token"] = token
        self.send(message)
        reply = self.recv(timeout)
        if reply.get("op") != "opened":
            raise ConnectionError(f"open failed: {reply}")
        self.session_id = reply.get("session")
        self.token = reply.get("token")
        return reply

    def send_frame(self, data: dict, tag=None) -> None:
        message: dict = {"op": "frame", "data": data}
        if tag is not None:
            message["tag"] = tag
        self.send(message)

    def next_result(self, timeout: float | None = 30.0) -> dict:
        """Skip to the next ``result`` message (busy/rejected and
        other interleaved notifications are returned by ``recv``;
        this helper drops them -- use ``recv`` when they matter)."""
        while True:
            message = self.recv(timeout)
            if message.get("op") == "result":
                return message

    def close(self, timeout: float | None = 10.0) -> None:
        try:
            self.send({"op": "close"})
            while True:
                if self.recv(timeout).get("op") == "closed":
                    break
        except (ws.WsClosed, OSError):
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self) -> "GatewayClient":
        return self

    def __exit__(self, *_exc) -> None:
        self.close()
