"""Multi-tenant streaming gateway (ISSUE 12): the production front
door.  ``qos`` is the one admission authority the engine's four former
admission planes consult; ``server`` is the HTTP + WebSocket service
that funnels client connections into pipeline streams; ``loadgen`` is
the open-loop mixed-tenant load generator the bench and CLI drive.

Import discipline: this package root re-exports only the jax-free QoS
authority (the engine seams import it on their hot paths); the server
and loadgen are imported lazily so ``pipeline/stages.py`` importing
``gateway.qos`` never drags sockets or the WS codec into every
process.
"""

from .qos import (DEFAULT_CLASS, QOS_CLASSES, QosScheduler, TokenBucket,
                  qos_spec_error)

__all__ = ["QosScheduler", "TokenBucket", "QOS_CLASSES",
           "DEFAULT_CLASS", "qos_spec_error", "GatewayServer",
           "GatewayClient", "run_loadgen"]


def __getattr__(name):
    if name in ("GatewayServer",):
        from .server import GatewayServer
        return GatewayServer
    if name in ("GatewayClient",):
        from .client import GatewayClient
        return GatewayClient
    if name in ("run_loadgen",):
        from .loadgen import run_loadgen
        return run_loadgen
    raise AttributeError(name)
