"""The gateway service: HTTP + WebSocket front door (ISSUE 12).

One listening socket multiplexes thousands of client connections into
pipeline streams:

- **WebSocket** (``/v1/stream``) carries streaming sessions: a client
  opens a session (tenant + priority class + optional per-frame
  deadline), sends frames, and receives results **in ingest order** --
  the session maps 1:1 onto a pipeline stream, so the engine's
  reorder-buffer delivery contract IS the session's ordering
  guarantee.  Sessions survive reconnects (``open`` with an existing
  session id ATTACHES: the new connection takes over, results follow
  it); a dangling disconnect destroys the session's stream so parked
  frames and swag tensors never leak.
- **HTTP** carries request/response (``POST /v1/frames``: one frame in,
  one result out, a one-shot session under the hood) plus ``/healthz``
  and ``/stats``.

Admission happens HERE, at the door, against the pipeline's
:class:`~aiko_services_tpu.gateway.qos.QosScheduler`: the tenant's
token bucket rejects over-rate frames before they touch the engine
(counted + ring-logged), the per-session window bounds in-flight
frames per client (backpressure: the client sees ``busy`` instead of
unbounded queueing), and everything admitted carries tenant/class into
the engine where the SAME scheduler orders every internal seam.

Transport notes: stdlib sockets only (tier-1 runs the whole path over
loopback, no external broker); one daemon thread per connection plus
one result pump per session -- the pump pays the ONE counted ledger
fetch per result (the gateway is a wire sink under the device-resident
swag contract, like ``_respond``'s process boundary).

Process-level fault domain (ISSUE 13): sessions are DECOUPLED from any
one pipeline.  Each session binds to a *target* -- the owning pipeline
in-process (fast path) or any pipeline discovered via registrar
records (wire path: ``create_stream``/``process_frame`` commands with
the gateway's own response topic).  When a bound pipeline's LWT fires
(registrar ``remove`` -> discovery ``_on_lost``), the gateway re-binds
the affected sessions to a surviving peer and commands it to ``adopt``
the dead pipeline's stream journal: the peer reconstructs the
sessions' streams, replays undelivered frames, and results resume on
the same WebSocket -- in order, deduped by the session-owned frame-id
sequence (the gateway assigns every frame's id, so 'already delivered'
means the same thing on every peer).  A standalone gateway
(``pipeline=None`` + a runtime) is the same machinery with no local
fast path: the production shape, where the front door's process is a
separate fault domain from every serving pipeline.

Idle-session reaping (``session_idle_ms``): a client that vanished
without a FIN -- its host died, its NAT forgot the mapping -- must not
pin a stream, its window slots and its tenant's in-flight budget until
process exit.  The reaper pings idle sessions (RFC 6455 ping; any
client speaking the shared codec pongs automatically) and frees the
session when a full idle window passes with no frames and no pongs.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import uuid

from . import ws
from .qos import QosScheduler
from ..observability.metrics import MetricsRegistry
from ..observability.tracing import (TraceBuffer, decode_spans,
                                     make_span, mint_id)
from ..utils import get_logger, generate, parse, parse_number

__all__ = ["GatewayServer", "json_safe", "decode_data"]

_logger = get_logger("aiko.gateway")

_HTTP_TIMEOUT_S = 30.0          # one-shot HTTP frame round trip
_ACCEPT_BACKLOG = 128
# Death -> adoption settle window: lets a DRAINING pipeline finish
# journaling frames that were in flight toward it when it announced
# its death, before the survivor reads the journal (see
# _on_peer_lost).
_FAILOVER_SETTLE_S = 0.08


def decode_data(data: dict) -> dict:
    """Client frame payload -> engine swag: JSON lists of numbers
    become numpy arrays (float32 when any member is fractional,
    int32 otherwise -- the accelerator-native dtypes), scalars and
    strings pass through.  A ``{"__tensor__": [...], "dtype": "..."}``
    wrapper forces an explicit dtype."""
    import numpy as np

    def convert(value):
        if isinstance(value, dict):
            if "__tensor__" in value:
                return np.asarray(value["__tensor__"],
                                  dtype=np.dtype(
                                      value.get("dtype", "float32")))
            return {key: convert(entry)
                    for key, entry in value.items()}
        if isinstance(value, list):
            flat = value
            while isinstance(flat, list) and flat \
                    and isinstance(flat[0], list):
                # A mixed nested/scalar level ([[1,2], 3]) is ragged:
                # fall through to the per-entry path, never crash.
                if not all(isinstance(sub, list) for sub in flat):
                    flat = []
                    break
                flat = [entry for sub in flat for entry in sub]
            if flat and all(isinstance(entry, (int, float))
                            and not isinstance(entry, bool)
                            for entry in flat):
                dtype = np.float32 if any(
                    isinstance(entry, float) for entry in flat) \
                    else np.int32
                try:
                    return np.asarray(value, dtype=dtype)
                except ValueError:      # ragged: pass through as-is
                    return value
            return [convert(entry) for entry in value]
        return value

    return {str(key): convert(value) for key, value in
            (data or {}).items()}


def json_safe(value):
    """Swag values -> JSON-encodable (arrays become nested lists,
    scalars become numbers, anything opaque becomes its type name --
    the recorder's redaction fallback, applied at the wire)."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, bytes):
        return value.decode("latin-1")
    if isinstance(value, dict):
        return {str(key): json_safe(entry)
                for key, entry in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_safe(entry) for entry in value]
    tolist = getattr(value, "tolist", None)
    if tolist is not None:
        try:
            return tolist()
        except Exception:
            pass
    item = getattr(value, "item", None)
    if item is not None:
        try:
            return item()
        except Exception:
            pass
    return f"<{type(value).__name__}>"


class _Session:
    """One gateway session <-> one pipeline stream."""

    def __init__(self, session_id: str, tenant: str, qos_class: str,
                 deadline_ms: float, window: int):
        import queue as queue_module
        self.session_id = session_id
        # Attach credential: minted on first open, returned in the
        # ``opened`` ack, REQUIRED to attach -- a client that merely
        # guesses a session id cannot hijack another tenant's stream.
        self.token = uuid.uuid4().hex
        self.stream_id = f"gw/{session_id}"
        self.tenant = tenant
        self.qos_class = qos_class
        self.deadline_ms = deadline_ms
        self.window = window
        self.queue = queue_module.Queue()   # engine queue_response
        self.conn: socket.socket | None = None
        self.send_lock = threading.Lock()
        self.state_lock = threading.Lock()
        self.inflight = 0
        self.sent = 0
        self.results = 0
        self.sent_times: list[float] = []   # FIFO; results are in-order
        self.closing = False
        self.pump: threading.Thread | None = None
        # Process fault domain (ISSUE 13): which pipeline this session
        # is bound to (None = the gateway's own pipeline, in-process;
        # a topic path = the wire binding), the SESSION-owned frame-id
        # sequence every target shares, and the last frame id actually
        # delivered to the client -- the failover dedupe line (a
        # replayed frame at or below it was already answered).
        self.target: str | None = None
        self.frame_seq = 0
        self.last_delivered = -1
        self.last_activity = time.monotonic()
        # Door-to-decode tracing: frame_id -> (trace_id, root span id,
        # wall start, monotonic start, admission-wait ms).  Bounded by
        # the session window (only admitted frames enter); the pump
        # pops each entry when its result is delivered (or deduped).
        self.trace_pending: dict[int, tuple] = {}
        # Retransmit line: frame_id -> (data, trace) for every frame
        # dispatched but not yet answered.  Journal adoption replays
        # what the dead pipeline INGESTED; a frame still in wire
        # transit at the kill was never journaled anywhere, so the
        # gateway -- the only party that still holds it -- re-fires
        # its copy after re-bind.  Bounded by the session window.
        self.unanswered: dict[int, tuple] = {}

    def next_frame_id(self) -> int:
        with self.state_lock:
            frame_id = self.frame_seq
            self.frame_seq += 1
            return frame_id

    def touch(self) -> None:
        self.last_activity = time.monotonic()

    def take_slot(self) -> "float | None":
        """Reserve one window slot; returns the stamp to pass to
        ``untake_slot`` if the frame is later refused (rate), or None
        when the window is full."""
        with self.state_lock:
            if self.closing or self.inflight >= self.window:
                return None
            self.inflight += 1
            self.sent += 1
            stamp = time.monotonic()
            self.sent_times.append(stamp)
            return stamp

    def untake_slot(self, stamp: float) -> None:
        """Undo a reservation for a frame that never entered the
        engine (token-bucket reject after the slot was taken): no
        result will arrive, so its stamp must not pair with one."""
        with self.state_lock:
            self.inflight = max(0, self.inflight - 1)
            self.sent = max(0, self.sent - 1)
            try:
                self.sent_times.remove(stamp)
            except ValueError:
                pass

    def finish_slot(self) -> float:
        """-> e2e seconds for the (in-order) completed frame."""
        with self.state_lock:
            self.inflight = max(0, self.inflight - 1)
            self.results += 1
            started = self.sent_times.pop(0) if self.sent_times else None
        return 0.0 if started is None else time.monotonic() - started


class GatewayServer:
    """Serve a front door on ``host:port`` (0 = kernel-assigned,
    echoed on ``.port``) -- for one pipeline (``gateway: on``,
    in-process fast path + failover to discovered peers) or standalone
    (``pipeline=None`` with a ``runtime``: every session binds to a
    discovered pipeline over the wire, so the gateway survives any
    serving process's death)."""

    def __init__(self, pipeline=None, host: str = "127.0.0.1",
                 port: int = 0, runtime=None,
                 session_idle_ms: float = 0.0, name: str = "gateway"):
        self.pipeline = pipeline
        self.name = name
        # Lazy default policy: the server may bind BEFORE the pipeline
        # finishes constructing (the endpoint is advertised as a
        # registrar tag, so it binds pre-registration like the tensor
        # pipe); the ``qos`` property below always reads the
        # pipeline's live scheduler first.
        self._default_qos: QosScheduler | None = None
        self.sessions: dict[str, _Session] = {}
        self._sessions_lock = threading.Lock()
        self._http_seq = 0
        self._stopped = False
        # Failover plane (ISSUE 13): discovered peer pipelines
        # (topic_path -> service name), the wire-response plumbing,
        # and the counters the failover tests assert on.
        self.runtime = None
        self._response_topic: str | None = None
        self._discovery = None
        self._peers: dict[str, str] = {}
        self._peers_lock = threading.Lock()
        self._http_waits: dict[str, object] = {}
        # Failovers that found NO survivor wait here; the next
        # _on_peer_found replays them, so sessions genuinely "stall
        # until one appears" instead of stalling forever.
        self._pending_failovers: list[tuple] = []
        self.failovers = 0
        self.sessions_reaped = 0
        # Fleet-controller routing (ISSUE 20): when the controller
        # scales the process pool, new sessions spread least-loaded
        # across home + peers instead of always binding home -- this
        # is how a freshly spawned peer takes load.
        self.balance = False
        # Observability plane (ISSUE 19): a standalone gateway owns its
        # registry + trace buffer; with a pipeline in-process both
        # delegate to its telemetry so gateway spans and pipeline spans
        # land in ONE buffer (TraceBuffer.add merges by trace_id).
        self._own_registry: MetricsRegistry | None = None
        self._own_traces: TraceBuffer | None = None
        #: fleet aggregator serving /fleet* when attached (the owning
        #: pipeline wires one under ``fleet: on``, or the operator sets
        #: it on a standalone gateway).
        self.fleet = None
        self._slo_gauge_stamp = 0.0
        # Idle-session reaping (``session_idle_ms``; 0 = off).
        self.session_idle_ms = max(0.0, float(session_idle_ms or 0.0))
        self._reaper: threading.Thread | None = None
        self._sock = socket.create_server((host, int(port)),
                                          backlog=_ACCEPT_BACKLOG)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"gateway-accept-{self.port}")
        self._accept_thread.start()
        if self.session_idle_ms:
            self._reaper = threading.Thread(
                target=self._reap_loop, daemon=True,
                name=f"gateway-reaper-{self.port}")
            self._reaper.start()
        if runtime is not None:
            self.attach_runtime(runtime)
        _logger.info("gateway front door on %s:%d (/v1/stream ws, "
                     "/v1/frames http)", host, self.port)

    def attach_runtime(self, runtime) -> None:
        """Join the service fabric: a private response topic for wire
        frame results, plus discovery of every pipeline service --
        the peer pool sessions fail over to (and bind to directly, in
        standalone mode).  Called by the owning Pipeline AFTER its
        actor registration (the gateway binds its socket before the
        runtime exists), or at construction when standalone."""
        if self.runtime is not None or runtime is None:
            return
        self.runtime = runtime
        self._response_topic = \
            f"{runtime.topic_path_process}/gateway/{self.port}"
        runtime.add_message_handler(self._on_wire_response,
                                    self._response_topic)
        # Deferred import (cycle: pipeline -> gateway at bind time),
        # but the ONE protocol authority -- a hand-copied literal here
        # would silently match nothing if the version ever bumps.
        from ..pipeline.pipeline import PROTOCOL_PIPELINE
        from ..services import ServiceFilter, do_discovery
        self._discovery = do_discovery(
            runtime, ServiceFilter(protocol=PROTOCOL_PIPELINE),
            add_handler=self._on_peer_found,
            remove_handler=self._on_peer_lost)

    # -- peer pool + failover ----------------------------------------------

    def _home_topic(self) -> str | None:
        pipeline = self.pipeline
        return None if pipeline is None \
            else getattr(pipeline, "topic_path", None)

    def _home_alive(self) -> bool:
        return self.pipeline is not None \
            and not getattr(self.pipeline, "_killed", False) \
            and not getattr(self.pipeline, "_draining", False) \
            and not getattr(self.pipeline, "_drained", False)

    def _on_peer_found(self, record, proxy) -> None:
        if record.topic_path == self._home_topic():
            return                      # the in-process fast path
        with self._peers_lock:
            self._peers[record.topic_path] = record.name
        _logger.info("gateway: pipeline peer %s (%s)", record.name,
                     record.topic_path)
        if self._pending_failovers:
            # Sessions stalled on an earlier no-survivor death: this
            # peer is their survivor.  Re-run the completion (it
            # re-computes the affected set; sessions that closed
            # meanwhile drop out).
            pending, self._pending_failovers = \
                self._pending_failovers, []
            for dead_topic, dead_name, home_died in pending:
                self._complete_failover(dead_topic, dead_name,
                                        home_died)

    def _pick_target(self) -> "str | None":
        """Binding for a NEW session: the in-process pipeline when it
        is alive, else any discovered peer, else the empty sentinel
        (no backend -- the open is refused).  Under ``balance`` (the
        fleet controller runs a process pool) the session goes to the
        least-loaded target across home + peers, home winning ties."""
        home = self._home_alive()
        with self._peers_lock:
            peers = list(self._peers)
        if self.balance and peers:
            counts: dict = {peer: 0 for peer in peers}
            if home:
                counts[None] = 0
            for session in list(self.sessions.values()):
                if session.target in counts:
                    counts[session.target] += 1
            if counts:
                return min(counts, key=lambda target:
                           (counts[target], target is not None,
                            target or ""))
        if home:
            return None
        for topic in peers:
            return topic
        return ""

    def _on_peer_lost(self, record, proxy=None) -> None:
        """A bound pipeline died (LWT -> registrar remove -> here) or
        drained away: after a short settle window, re-bind its
        sessions to a survivor and command the adoption of its
        journal."""
        topic = record.topic_path
        home_died = topic == self._home_topic()
        with self._peers_lock:
            self._peers.pop(topic, None)
        affected = [session for session in list(self.sessions.values())
                    if (session.target == topic
                        or (session.target is None and home_died))]
        if not affected:
            return
        # Settle before adopting: a DRAINING pipeline is still
        # journaling frames that were already in flight toward it
        # when it announced its death (they are held for the adopter,
        # not run).  Reading the journal immediately would race those
        # stragglers -- the one frame the zero-drop contract would
        # lose.  A killed pipeline journals nothing in the window, so
        # the delay only costs MTTR.  Registrar CHURN also lands here
        # (the mirror purges and fires a remove per record, pipelines
        # not dead): give the re-share a full extra second, and let
        # the completion's peer-is-back check turn it into a no-op --
        # returning early instead used to skip a genuine death
        # forever when the removal raced a cache refresh.
        cache = getattr(self._discovery, "cache", None)
        settle = _FAILOVER_SETTLE_S
        if cache is not None and cache.state != "ready":
            settle += 1.0
        self.runtime.engine.add_oneshot_timer(
            lambda: self._complete_failover(topic, record.name,
                                            home_died), settle)

    def _complete_failover(self, topic: str, dead_name: str,
                           home_died: bool) -> None:
        with self._peers_lock:
            if topic in self._peers:
                return              # churn, not death: peer re-added
        affected = [session for session in list(self.sessions.values())
                    if (session.target == topic
                        or (session.target is None and home_died))]
        if not affected:
            return
        survivor = None
        with self._peers_lock:
            for peer in self._peers:
                survivor = peer
                break
        if survivor is None and not home_died and self._home_alive():
            survivor = ""               # fail back to the local path
        if survivor is None:
            self._pending_failovers.append((topic, dead_name,
                                            home_died))
            _logger.error(
                "gateway: pipeline %s died with %d bound session(s) "
                "and no surviving peer; sessions stall until one "
                "appears", dead_name, len(affected))
            return
        self.failovers += 1
        telemetry = getattr(self.pipeline, "telemetry", None)
        if telemetry is not None:
            telemetry.registry.count("pipeline_failovers")
        # Adoption FIRST, then re-bind: the peer's mailbox is FIFO, so
        # the journal replay lands before any new frame the re-bound
        # sessions send it.
        self._send_adopt(survivor, dead_name)
        refired = 0
        for session in affected:
            session.target = None if survivor == "" else survivor
            # Re-fire the session's unanswered frames at the new
            # target.  Adoption only replays frames the dead pipeline
            # journaled; one still in wire transit at the kill never
            # reached any journal, and without this re-send it is
            # simply gone -- the client stalls a window slot forever.
            # Frames the adopter DOES replay arrive first (same FIFO
            # mailbox), so our duplicate re-ingests into a silently
            # skipped slot and delivery dedupe keeps the client's
            # exactly-once, in-order contract.
            with session.state_lock:
                unanswered = sorted(session.unanswered.items())
            for frame_id, (data, trace) in unanswered:
                self._dispatch_frame(session, data, frame_id,
                                     trace=trace)
                refired += 1
        if refired:
            registry = self._registry()
            if registry is not None:
                registry.count("gateway_refired_frames", refired)
        _logger.warning(
            "gateway: pipeline %s died; %d session(s) re-bound to %s "
            "(journal adoption requested, %d in-flight frame(s) "
            "re-fired)", dead_name, len(affected), "local pipeline"
            if survivor == "" else survivor, refired)

    def _send_adopt(self, survivor: str, dead_name: str) -> None:
        if survivor == "" and self.pipeline is not None:
            self.pipeline.post_self(
                "adopt", [dead_name, self._response_topic])
        elif self.runtime is not None:
            self.runtime.message.publish(
                f"{survivor}/in",
                generate("adopt", [dead_name,
                                   self._response_topic or ""]))

    # -- wire binding ------------------------------------------------------

    def _create_wire_stream(self, target: str, stream_id: str,
                            parameters: dict) -> None:
        self.runtime.message.publish(
            f"{target}/in",
            generate("create_stream", [stream_id, dict(parameters)]))

    def _send_wire_frame(self, target: str, stream_id: str,
                         frame_id: int, data: dict,
                         trace_id: str | None = None,
                         trace_parent: str | None = None) -> None:
        from ..pipeline.codec import encode_frame_data
        header = {"stream_id": stream_id, "frame_id": int(frame_id),
                  "response_topic": self._response_topic}
        if trace_id:
            # Door-to-decode: the remote pipeline stamps its spans
            # under the gateway's root and returns them in the
            # response header (the PR 4 remote-hop machinery).
            header["trace_id"] = trace_id
            header["trace_parent"] = trace_parent
        self.runtime.message.publish(
            f"{target}/in",
            generate("process_frame",
                     [header, encode_frame_data(data)]))

    def _dispatch_frame(self, session: _Session, data: dict,
                        frame_id: int, trace: tuple | None = None) -> None:
        """Route one admitted frame to the session's current target.
        Every frame carries the session-owned id, so delivery dedupe
        holds across failovers regardless of which pipeline answers."""
        trace_id = trace[0] if trace else None
        trace_parent = trace[1] if trace else None
        with session.state_lock:
            session.unanswered[frame_id] = (data, trace)
        if session.target is None and self.pipeline is not None:
            self.pipeline.process_frame_local(
                data, stream_id=session.stream_id,
                queue_response=session.queue, frame_id=frame_id,
                trace_id=trace_id, trace_parent=trace_parent)
        elif session.target:
            self._send_wire_frame(session.target, session.stream_id,
                                  frame_id, data, trace_id=trace_id,
                                  trace_parent=trace_parent)
        else:
            _logger.warning("gateway: session %s has no live target; "
                            "frame %d dropped at the door",
                            session.session_id, frame_id)

    def _on_wire_response(self, topic: str, payload) -> None:
        """A wire-bound pipeline answered: route the result onto the
        owning session's queue (the same path local results take)."""
        try:
            command, parameters = parse(payload)
        except Exception:
            return
        if command != "process_frame_response" or len(parameters) < 1:
            return
        header = dict(parameters[0] or {})
        body = dict(parameters[1] or {}) if len(parameters) > 1 else {}
        stream_id = str(header.get("stream_id", ""))
        okay = str(header.get("okay", "true")).lower() != "false"
        frame_id = parse_number(header.get("frame_id"), None)
        from ..pipeline.codec import decode_frame_data
        try:
            decoded = decode_frame_data(body)
        except Exception as error:
            decoded, okay = {}, False
            header.setdefault("diagnostic",
                              f"undecodable result ({error})")
        spans_text = header.get("spans")
        if spans_text:
            # The wire-bound pipeline's spans for this frame (it saw
            # our trace_id, so it returned them instead of keeping a
            # private trace): merge them under the gateway's trace.
            spans = decode_spans(spans_text)
            if spans:
                traces = self._traces()
                if traces is not None:
                    traces.add(spans[0].get("trace_id"), spans, okay)
        entry = (stream_id,
                 None if frame_id is None else int(frame_id),
                 decoded, {}, okay,
                 str(header.get("diagnostic", "")))
        if stream_id.startswith("gw/"):
            with self._sessions_lock:
                session = self.sessions.get(stream_id[3:])
            if session is not None:
                session.queue.put(entry)
        elif stream_id in self._http_waits:
            waiter = self._http_waits.get(stream_id)
            if waiter is not None:
                waiter.put(entry)

    # -- idle-session reaping ----------------------------------------------

    def _reap_loop(self) -> None:
        idle_s = self.session_idle_ms / 1000.0
        interval = max(0.02, idle_s / 4.0)
        while not self._stopped:
            time.sleep(interval)
            now = time.monotonic()
            for session in list(self.sessions.values()):
                idle = now - session.last_activity
                if idle >= idle_s:
                    self._reap_session(session, idle)
                elif idle >= idle_s / 2.0:
                    # Half the window gone quiet: ping.  A live client
                    # pongs (the shared codec answers in recv) and the
                    # on_frame stamp resets the clock; a vanished one
                    # stays silent into the reap above.
                    self._ws_ping(session)

    def _reap_session(self, session: _Session, idle: float) -> None:
        self.sessions_reaped += 1
        _logger.warning(
            "gateway: reaping session %s (idle %.0f ms >= "
            "session_idle_ms %.0f): stream, window slots and QoS "
            "budget freed", session.session_id, idle * 1000.0,
            self.session_idle_ms)
        telemetry = getattr(self.pipeline, "telemetry", None)
        if telemetry is not None:
            telemetry.registry.count("gateway_sessions_reaped")
        self._destroy_session(session)
        self._close_conn(session)

    @staticmethod
    def _ws_ping(session: _Session) -> None:
        with session.send_lock:
            conn = session.conn
            if conn is None:
                return
            try:
                ws.send_frame(conn, b"", ws.OP_PING)
            except OSError:
                session.conn = None

    @property
    def qos(self) -> QosScheduler:
        """The pipeline's scheduler when it has one; otherwise a
        default-policy instance so the door still resolves classes and
        session windows (no rate limits, no budgets)."""
        scheduler = getattr(self.pipeline, "qos", None)
        if scheduler is not None:
            return scheduler
        if self._default_qos is None:
            self._default_qos = QosScheduler()
        return self._default_qos

    # -- observability plane (ISSUE 19) ------------------------------------

    def _registry(self) -> "MetricsRegistry | None":
        """The metrics registry gateway series land in: the pipeline's
        (one process, one registry) or the gateway's own when
        standalone.  None when the pipeline disabled telemetry -- the
        door honors ``telemetry: off`` like every other plane."""
        if self.pipeline is not None:
            telemetry = getattr(self.pipeline, "telemetry", None)
            return None if telemetry is None else telemetry.registry
        if self._own_registry is None:
            self._own_registry = MetricsRegistry()
        return self._own_registry

    def _traces(self) -> "TraceBuffer | None":
        """Trace buffer, same ownership rule as :meth:`_registry`."""
        if self.pipeline is not None:
            telemetry = getattr(self.pipeline, "telemetry", None)
            return None if telemetry is None else telemetry.traces
        if self._own_traces is None:
            self._own_traces = TraceBuffer()
        return self._own_traces

    def _mint_trace(self, session: "_Session | None", frame_id: int,
                    admit_ms: float) -> "tuple | None":
        """Root a new door-to-decode trace for one admitted frame:
        (trace_id, root span id, wall start, monotonic start,
        admission-wait ms).  The dispatched frame carries trace_id +
        the root as its parent, so every downstream span -- origin
        pipeline, remote hops, LLM decode blocks -- joins THIS trace."""
        if self._traces() is None:
            return None
        entry = (mint_id(), mint_id(), time.time(), time.monotonic(),
                 admit_ms)
        if session is not None:
            session.trace_pending[frame_id] = entry
        return entry

    def _finish_trace(self, session: "_Session | None", entry: tuple,
                      frame_id, stream_id: str, okay: bool,
                      pump_start: float, extra_spans=None) -> str:
        """Close the gateway's spans (root session span = door-to-door
        e2e, admission wait, result pump) and merge them into the
        buffer under the frame's trace_id."""
        trace_id, root, wall_start, mono_start, admit_ms = entry
        now = time.monotonic()
        spans = [make_span(trace_id, root, None,
                           f"gateway:{self.name}", "gateway",
                           process=self.name, stream=stream_id,
                           frame=frame_id, start=wall_start,
                           duration_ms=(now - mono_start) * 1000.0,
                           status="ok" if okay else "error"),
                 make_span(trace_id, mint_id(), root, "gateway:admit",
                           "gateway", process=self.name,
                           stream=stream_id, frame=frame_id,
                           start=wall_start, duration_ms=admit_ms),
                 make_span(trace_id, mint_id(), root, "gateway:pump",
                           "gateway", process=self.name,
                           stream=stream_id, frame=frame_id,
                           start=wall_start
                           + (pump_start - mono_start),
                           duration_ms=(now - pump_start) * 1000.0)]
        if extra_spans:
            spans.extend(extra_spans)
        traces = self._traces()
        if traces is not None:
            traces.add(trace_id, spans, okay)
        return trace_id

    def _note_slo(self, tenant: str, qos_class: str,
                  e2e_ms: "float | None", okay: bool) -> None:
        """One SLO observation (delivered result or latency-less bad
        event), plus the fast-burn check: a burn > 1 fires the
        remediation pair (ring event + debounced black-box dump, via
        the pipeline's event loop) and is counted.  Burn gauges
        refresh at most once a second."""
        slo = self.qos.slo
        if slo is None:
            return
        label = self.qos.tenant(tenant).name
        slo.observe(label, qos_class, e2e_ms, okay)
        registry = self._registry()
        fired = slo.fast_burns()
        for burn_tenant, burn_class, burn in fired:
            if registry is not None:
                registry.count("slo_fast_burns", tenant=burn_tenant,
                               cls=burn_class)
            _logger.warning(
                "gateway: SLO fast burn %.2fx (tenant %s, class %s)",
                burn, burn_tenant, burn_class)
        now = time.monotonic()
        burns = None
        if fired or now - self._slo_gauge_stamp >= 1.0:
            self._slo_gauge_stamp = now
            burns = slo.burn_rates(now)
            if registry is not None:
                for tenant_name, classes in burns.items():
                    for class_name, entry in classes.items():
                        registry.gauge("slo_burn", entry["burn"],
                                       tenant=tenant_name,
                                       cls=class_name)
        if self.pipeline is not None and (fired or burns is not None):
            self.pipeline.post_self("note_slo_burn",
                                    [list(fired), burns])

    # -- plumbing ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return                      # closed
            threading.Thread(target=self._serve_connection,
                             args=(conn,), daemon=True,
                             name="gateway-conn").start()

    def stop(self) -> None:
        self._stopped = True
        if self._discovery is not None:
            self._discovery.terminate()
            self._discovery = None
        if self.runtime is not None and self._response_topic:
            self.runtime.remove_message_handler(self._on_wire_response,
                                                self._response_topic)
        try:
            # shutdown BEFORE close: close() alone does not wake a
            # thread blocked in accept(), and the kernel socket kept
            # accepting connections for process lifetime (found by
            # the create-failure leak test).
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._sessions_lock:
            sessions, self.sessions = dict(self.sessions), {}
        for session in sessions.values():
            session.closing = True
            self._close_conn(session)
            session.queue.put(None)     # retire the pump thread

    @staticmethod
    def _close_conn(session: _Session) -> None:
        conn, session.conn = session.conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def session_count(self) -> int:
        with self._sessions_lock:
            return len(self.sessions)

    # -- connection handling -----------------------------------------------

    def _serve_connection(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(_HTTP_TIMEOUT_S)
            head, body_start = self._read_head(conn)
            if head is None:
                return
            request_line, headers = head
            method, _, rest = request_line.partition(" ")
            path = rest.split(" ", 1)[0]
            upgrade = ws.server_handshake(headers)
            if upgrade is not None:
                conn.sendall(upgrade)
                conn.settimeout(None)
                self._serve_ws(conn)
                return
            self._serve_http(conn, method.upper(), path, headers,
                             body_start)
        except (OSError, ws.WsClosed, ConnectionError):
            pass
        except Exception:
            _logger.exception("gateway connection failed")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _read_head(conn: socket.socket):
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(8192)
            if not chunk:
                return None, b""
            data += chunk
            if len(data) > 1 << 20:
                raise ConnectionError("oversized request head")
        head, _, remainder = data.partition(b"\r\n\r\n")
        lines = head.decode("latin-1").split("\r\n")
        headers = {}
        for line in lines[1:]:
            name, _, value = line.partition(":")
            headers[name.strip().lower()] = value.strip()
        return (lines[0], headers), remainder

    # -- HTTP --------------------------------------------------------------

    def _serve_http(self, conn, method: str, path: str, headers: dict,
                    body_start: bytes) -> None:
        if method == "GET" and path == "/healthz":
            with self._peers_lock:
                peers = len(self._peers)
            self._http_reply(conn, 200, {
                "ok": True, "sessions": self.session_count(),
                "streams": None if self.pipeline is None
                else len(self.pipeline.streams),
                "peers": peers})
            return
        if method == "GET" and path == "/stats":
            self._http_reply(conn, 200, {
                "sessions": self.session_count(),
                "qos": {} if self.pipeline is None
                else self.pipeline.qos_stats(),
                "failovers": self.failovers,
                "sessions_reaped": self.sessions_reaped})
            return
        if method == "GET" and (path in ("/metrics", "/metrics/raw",
                                         "/slo")
                                or path.startswith("/traces")
                                or path.startswith("/fleet")):
            self._serve_observability(conn, path.rstrip("/") or "/")
            return
        if method == "POST" and path == "/v1/frames":
            length = int(headers.get("content-length", "0"))
            body = body_start
            while len(body) < length:
                chunk = conn.recv(length - len(body))
                if not chunk:
                    raise ConnectionError("truncated request body")
                body += chunk
            try:
                request = json.loads(body.decode() or "{}")
            except json.JSONDecodeError as error:
                self._http_reply(conn, 400, {"error": f"bad JSON: "
                                                      f"{error}"})
                return
            self._serve_http_frame(conn, request)
            return
        self._http_reply(conn, 404,
                         {"error": "try /healthz, /stats, "
                                   "/v1/frames or ws /v1/stream"})

    def _serve_http_frame(self, conn, request: dict) -> None:
        """One-shot request/response: a private session/stream per
        request rides the same admission + delivery path as streaming
        sessions, then tears down."""
        tenant = str(request.get("tenant", "default"))
        qos_class = self.qos.resolve_class(request.get("class"), tenant)
        try:
            # Decode BEFORE admission or stream creation: a malformed
            # payload must cost a 400, not a burned rate token or a
            # leaked stream.
            data = decode_data(request.get("data"))
        except Exception as error:
            self._http_reply(conn, 400, {"error": "bad data",
                                         "detail": str(error)[:200]})
            return
        admit_start = time.monotonic()
        admitted, reason = self._admit(tenant, qos_class, None)
        admit_ms = (time.monotonic() - admit_start) * 1000.0
        if not admitted:
            self._note_slo(tenant, qos_class, None, False)
            self._http_reply(conn, 429, {"error": "rejected",
                                         "reason": reason})
            return
        registry = self._registry()
        if registry is not None:
            registry.observe("gateway_admit_wait_ms", admit_ms)
        with self._sessions_lock:
            self._http_seq += 1
            stream_id = f"gwhttp/{self.port}/{self._http_seq}"
        import queue as queue_module
        responses = queue_module.Queue()
        # One-shot streams opt out of the journal: there is no session
        # to adopt, and replaying them to a 504'd-and-gone client
        # would be wasted work on the survivor.
        parameters = {"tenant": tenant, "qos_class": qos_class,
                      "journal": "off"}
        deadline_ms = request.get("deadline_ms")
        if deadline_ms is not None:
            parameters["frame_deadline_ms"] = float(deadline_ms)
        pipeline = self.pipeline
        target = self._pick_target()
        if target == "":
            self._http_reply(conn, 503, {"error": "no backend"})
            return
        trace = self._mint_trace(None, 0, admit_ms)
        trace_id = None if trace is None else trace[0]
        trace_parent = None if trace is None else trace[1]
        if target is None:
            # Mailbox FIFO: the create lands before the ingest, so the
            # frame sees the session's tenant/class/deadline parameters.
            pipeline.post_self("create_stream_local",
                               [stream_id, parameters, None, 0,
                                responses])
            pipeline.process_frame_local(data, stream_id=stream_id,
                                         queue_response=responses,
                                         trace_id=trace_id,
                                         trace_parent=trace_parent)
        else:
            self._http_waits[stream_id] = responses
            self._create_wire_stream(target, stream_id, parameters)
            self._send_wire_frame(target, stream_id, 0, data,
                                  trace_id=trace_id,
                                  trace_parent=trace_parent)
        try:
            (_, frame_id, swag, metrics, okay, diagnostic) = \
                responses.get(timeout=_HTTP_TIMEOUT_S)
        except Exception:
            self._note_slo(tenant, qos_class, None, False)
            self._http_reply(conn, 504, {"error": "timed out"})
            return
        finally:
            self._http_waits.pop(stream_id, None)
            if target is None:
                pipeline.post_self("destroy_stream", [stream_id, True])
            else:
                self.runtime.message.publish(
                    f"{target}/in",
                    generate("destroy_stream", [stream_id, True]))
        pump_start = time.monotonic()
        bare = {key: value for key, value in swag.items()
                if "." not in key}
        if pipeline is not None:
            bare = pipeline.transfer_ledger.fetch(bare)
        e2e_ms = (time.monotonic() - trace[3]) * 1000.0 \
            if trace is not None \
            else float(metrics.get("time_pipeline", 0.0)) * 1000.0
        self._note_slo(tenant, qos_class, e2e_ms, okay)
        status = 200 if okay else 503
        reply = {
            "ok": bool(okay), "frame": frame_id,
            "data": json_safe(bare), "diagnostic": diagnostic,
            "e2e_ms": round(float(metrics.get("time_pipeline", 0.0))
                            * 1000.0, 3)}
        if trace is not None:
            reply["trace"] = self._finish_trace(
                None, trace, frame_id, stream_id, okay, pump_start)
        self._http_reply(conn, status, reply)

    def _serve_observability(self, conn, path: str) -> None:
        """The door's observability surface (ISSUE 19): the same
        /metrics, /metrics/raw and /traces shapes as the pipeline's
        MetricsServer (scraping a gateway and scraping a pipeline are
        the same act), /slo for the live burn snapshot, and /fleet*
        when a fleet aggregator is attached."""
        if path.startswith("/fleet"):
            fleet = self.fleet
            if fleet is None:
                self._http_reply(conn, 404, {
                    "error": "no fleet collector attached "
                             "(fleet: on)"})
            elif path == "/fleet":
                self._http_text_reply(conn, fleet.render_fleet_text())
            elif path == "/fleet/slo":
                self._http_reply(conn, 200, fleet.fleet_slo())
            elif path.startswith("/fleet/traces/"):
                trace = fleet.fleet_trace(
                    path[len("/fleet/traces/"):])
                if trace is None:
                    self._http_reply(conn, 404,
                                     {"error": "unknown trace"})
                else:
                    self._http_reply(conn, 200, trace)
            else:
                self._http_reply(conn, 404, {
                    "error": "try /fleet, /fleet/slo or "
                             "/fleet/traces/<id>"})
            return
        if path == "/slo":
            slo = self.qos.slo
            self._http_reply(conn, 200, {} if slo is None
                             else slo.snapshot())
            return
        registry = self._registry()
        if registry is None:
            self._http_reply(conn, 404, {"error": "telemetry disabled"})
            return
        if path == "/metrics":
            if self.pipeline is not None:
                text = self.pipeline.telemetry.metrics_text()
            else:
                text = registry.render_text()
            self._http_text_reply(conn, text)
        elif path == "/metrics/raw":
            if self.pipeline is not None:
                self.pipeline.telemetry.metrics_text()   # gauge refresh
            payload = registry.state()
            payload["pipeline"] = self.name \
                if self.pipeline is None else self.pipeline.name
            self._http_reply(conn, 200, payload)
        elif path == "/traces":
            traces = self._traces()
            self._http_reply(conn, 200, {"traces": traces.recent(50)})
        elif path.startswith("/traces/"):
            trace = self._traces().get(path[len("/traces/"):])
            if trace is None:
                self._http_reply(conn, 404, {"error": "unknown trace"})
            else:
                self._http_reply(conn, 200, trace)
        else:
            self._http_reply(conn, 404, {
                "error": "try /metrics, /metrics/raw, /traces or /slo"})

    @staticmethod
    def _http_reply(conn, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests", 503: "Unavailable",
                  504: "Gateway Timeout"}.get(status, "OK")
        conn.sendall((f"HTTP/1.1 {status} {reason}\r\n"
                      "Content-Type: application/json\r\n"
                      f"Content-Length: {len(body)}\r\n"
                      "Connection: close\r\n\r\n").encode() + body)

    @staticmethod
    def _http_text_reply(conn, text: str) -> None:
        body = text.encode()
        conn.sendall(("HTTP/1.1 200 OK\r\n"
                      "Content-Type: text/plain; version=0.0.4; "
                      "charset=utf-8\r\n"
                      f"Content-Length: {len(body)}\r\n"
                      "Connection: close\r\n\r\n").encode() + body)

    # -- admission ---------------------------------------------------------

    def _admit(self, tenant: str, qos_class: str,
               session: "_Session | None") -> tuple[bool, str]:
        """Front-door admission for one frame: token bucket first,
        then the session window (backpressure).  Counted + ring-logged
        both ways."""
        pipeline = self.pipeline
        # Slot FIRST (atomically, under the session lock), bucket
        # second: a backpressured frame must not burn one of the
        # tenant's rate tokens on the way to its ``busy``, and two
        # connections racing one session must not over-admit.
        stamp = None
        if session is not None:
            stamp = session.take_slot()
            if stamp is None:
                admitted, reason = False, "window"
            else:
                admitted, reason = self.qos.admit(tenant, qos_class)
                if not admitted:
                    session.untake_slot(stamp)
        else:
            admitted, reason = self.qos.admit(tenant, qos_class)
        telemetry = getattr(pipeline, "telemetry", None)
        recorder = getattr(pipeline, "recorder", None)
        # Metric labels carry the RESOLVED tenant entry's name, never
        # the raw client string: past LAZY_TENANT_CAP unknown names
        # share the default entry, so an attacker cycling tenant names
        # cannot grow the metrics registry without bound (the registry
        # never evicts label sets).
        label = self.qos.tenant(tenant).name
        if admitted:
            if telemetry is not None:
                telemetry.registry.count("gateway_admits",
                                         tenant=label, cls=qos_class)
            if recorder is not None:
                recorder.record(
                    "gw_admit",
                    None if session is None else session.stream_id,
                    None, label, None, {"cls": qos_class})
        else:
            if telemetry is not None:
                telemetry.registry.count("gateway_rejects",
                                         tenant=label, reason=reason)
            if recorder is not None:
                recorder.record(
                    "gw_reject",
                    None if session is None else session.stream_id,
                    None, label, None,
                    {"cls": qos_class, "reason": reason})
        return admitted, reason

    # -- WebSocket sessions ------------------------------------------------

    def _serve_ws(self, conn: socket.socket) -> None:
        session: _Session | None = None
        holder: dict = {"session": None}

        def on_frame(_opcode):
            # Liveness for the idle reaper: ANY wire frame from the
            # client -- data or the pong answering our ping.
            live = holder["session"]
            if live is not None:
                live.touch()

        try:
            while True:
                opcode, payload = ws.recv_message(conn,
                                                  on_frame=on_frame)
                try:
                    message = json.loads(payload.decode())
                except json.JSONDecodeError as error:
                    self._ws_send_raw(conn, {"op": "error",
                                             "error": f"bad JSON: "
                                                      f"{error}"})
                    continue
                op = str(message.get("op", ""))
                if op == "open":
                    opened = self._ws_open(conn, message)
                    if opened is not None:
                        session = opened
                        holder["session"] = session
                        session.touch()
                elif op == "frame":
                    self._ws_frame(conn, session, message)
                elif op == "close":
                    self._ws_close(conn, session)
                    session = None
                else:
                    self._ws_send_raw(conn, {"op": "error",
                                             "error": f"unknown op "
                                                      f"{op!r}"})
        except (ws.WsClosed, OSError, ConnectionError):
            pass
        finally:
            # Dangling disconnect: clean up the pipeline stream --
            # UNLESS another connection already attached (takeover),
            # in which case this socket no longer owns the session.
            if session is not None and session.conn is conn:
                self._destroy_session(session)

    def _ws_open(self, conn, message: dict) -> "_Session | None":
        session_id = str(message.get("session") or uuid.uuid4().hex[:12])
        tenant = str(message.get("tenant", "default"))
        qos_class = self.qos.resolve_class(message.get("class"), tenant)
        deadline_ms = float(message.get("deadline_ms") or 0.0)
        # The client may request a SMALLER window (tighter client-side
        # pipelining); the policy's session_window is the ceiling --
        # a huge requested window must not defeat backpressure.
        ceiling = max(1, int(self.qos.session_window))
        window = max(1, min(int(message.get("window") or ceiling),
                            ceiling))
        with self._sessions_lock:
            session = self.sessions.get(session_id)
            attached = session is not None
            if session is None:
                session = _Session(session_id, tenant, qos_class,
                                   deadline_ms, window)
                self.sessions[session_id] = session
        if attached:
            if str(message.get("token") or "") != session.token:
                # Attach is a takeover of a live stream: it requires
                # the credential minted at open, not just the id.
                self._ws_send_raw(conn, {"op": "error",
                                         "error": "bad session token"})
                return None
            # Takeover: results follow the new connection.
            with session.send_lock:
                session.conn = conn
            session.touch()
        else:
            target = self._pick_target()
            if target == "":
                with self._sessions_lock:
                    self.sessions.pop(session_id, None)
                self._ws_send_raw(conn, {"op": "error",
                                         "error": "no backend"})
                return None
            session.conn = conn
            session.target = target
            parameters = {"tenant": tenant, "qos_class": qos_class}
            if deadline_ms:
                parameters["frame_deadline_ms"] = deadline_ms
            if target is None:
                self.pipeline.post_self(
                    "create_stream_local",
                    [session.stream_id, parameters, None, 0,
                     session.queue])
            else:
                self._create_wire_stream(target, session.stream_id,
                                         parameters)
            session.pump = threading.Thread(
                target=self._pump_results, args=(session,),
                daemon=True, name=f"gateway-pump-{session_id}")
            session.pump.start()
        self._ws_send(session, {"op": "opened",
                                "session": session_id,
                                "token": session.token,
                                "attached": attached,
                                "class": session.qos_class,
                                "window": session.window})
        return session

    def _ws_frame(self, conn, session: _Session | None,
                  message: dict) -> None:
        if session is None or session.closing \
                or session.conn is not conn:
            # No session, a closed one, or a connection another attach
            # superseded: its frames must not auto-recreate the stream
            # under default tenancy (ingest_local would) or bill the
            # session's window.
            self._ws_send_raw(conn, {"op": "rejected",
                                     "reason": "no-session"})
            return
        try:
            # BEFORE admission: a malformed payload must cost a
            # ``rejected`` reply, never a taken window slot or (worse)
            # the whole connection.
            data = decode_data(message.get("data"))
        except Exception as error:
            self._ws_send(session, {"op": "rejected",
                                    "reason": "bad-data",
                                    "error": str(error)[:200]})
            return
        admit_start = time.monotonic()
        admitted, reason = self._admit(session.tenant,
                                       session.qos_class, session)
        admit_ms = (time.monotonic() - admit_start) * 1000.0
        if not admitted:
            if reason != "window":
                # A rate reject is an availability event against the
                # tenant's error budget; window backpressure is the
                # client's own pipelining, not a served failure.
                self._note_slo(session.tenant, session.qos_class,
                               None, False)
            payload = {"op": "busy" if reason == "window"
                       else "rejected",
                       "reason": reason, "inflight": session.inflight}
            tag = message.get("tag")
            if tag is not None:
                payload["tag"] = tag
            self._ws_send(session, payload)
            return
        registry = self._registry()
        if registry is not None:
            registry.observe("gateway_admit_wait_ms", admit_ms)
        frame_id = session.next_frame_id()
        trace = self._mint_trace(session, frame_id, admit_ms)
        self._dispatch_frame(session, data, frame_id, trace=trace)

    def _ws_close(self, conn, session: _Session | None) -> None:
        # Only the session's CURRENT connection may destroy it: a
        # superseded connection's buffered close must not tear down a
        # session another client just took over.
        if session is not None and session.conn is conn:
            self._destroy_session(session)
        self._ws_send_raw(conn, {"op": "closed"})

    def _destroy_session(self, session: _Session) -> None:
        with self._sessions_lock:
            self.sessions.pop(session.session_id, None)
        session.closing = True
        if session.target is None and self.pipeline is not None:
            self.pipeline.post_self("destroy_stream",
                                    [session.stream_id, True])
        elif session.target and self.runtime is not None:
            self.runtime.message.publish(
                f"{session.target}/in",
                generate("destroy_stream", [session.stream_id, True]))
        session.queue.put(None)             # wake + retire the pump

    def _pump_results(self, session: _Session) -> None:
        """Per-session result pump: engine responses (already in
        ingest order -- the stream's reorder buffer) go out on
        whatever connection currently owns the session.  Pays the one
        counted ledger fetch per result: the wire-sink contract."""
        pipeline = self.pipeline
        while True:
            entry = session.queue.get()
            if entry is None:
                return
            (_, frame_id, swag, metrics, okay, diagnostic) = entry
            try:
                frame_seq = int(frame_id)
            except (TypeError, ValueError):
                frame_seq = None
            if frame_seq is not None:
                with session.state_lock:
                    session.unanswered.pop(frame_seq, None)
                    if frame_seq <= session.last_delivered:
                        # Failover dedupe: the dead pipeline answered
                        # this frame before dying (or the journal's
                        # done record raced the crash) and the
                        # adopter replayed it anyway -- the client
                        # must see each id exactly once, in order.
                        session.trace_pending.pop(frame_seq, None)
                        continue
                    session.last_delivered = frame_seq
            e2e_s = session.finish_slot()
            pump_start = time.monotonic()
            bare = {key: value for key, value in swag.items()
                    if "." not in key}
            if pipeline is not None:
                try:
                    bare = pipeline.transfer_ledger.fetch(bare)
                except Exception as error:
                    okay, diagnostic = False, f"result fetch: {error}"
                    bare = {}
            registry = self._registry()
            if registry is not None:
                registry.observe("gateway_e2e_ms", e2e_s * 1000.0,
                                 cls=session.qos_class,
                                 tenant=self.qos.tenant(
                                     session.tenant).name)
            self._note_slo(session.tenant, session.qos_class,
                           e2e_s * 1000.0, okay)
            pending = None if frame_seq is None else \
                session.trace_pending.pop(frame_seq, None)
            payload = {
                "op": "result", "frame": frame_id, "ok": bool(okay),
                "data": json_safe(bare), "diagnostic": diagnostic,
                "e2e_ms": round(e2e_s * 1000.0, 3)}
            if pending is not None:
                payload["trace"] = pending[0]
                # Finish BEFORE the send: once the client holds a
                # result naming this trace id, /traces/<id> must
                # resolve it (the pump span ends at hand-off to the
                # socket, not after the write).
                self._finish_trace(session, pending, frame_seq,
                                   session.stream_id, okay, pump_start)
            self._ws_send(session, payload)

    def _ws_send(self, session: _Session, payload: dict) -> None:
        with session.send_lock:
            conn = session.conn
            if conn is None:
                return
            try:
                ws.send_frame(conn, json.dumps(payload))
            except OSError:
                # The pump outlives a dropped connection; results are
                # simply not deliverable until a client re-attaches.
                session.conn = None

    @staticmethod
    def _ws_send_raw(conn, payload: dict) -> None:
        try:
            ws.send_frame(conn, json.dumps(payload))
        except OSError:
            pass
